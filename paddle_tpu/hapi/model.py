"""hapi.Model (reference: python/paddle/hapi/model.py — fit :1472,
evaluate, predict, save/load)."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..io import DataLoader, Dataset
from ..observability.train import batch_samples
from .. import framework
from .callbacks import CallbackList, ProgBarLogger

__all__ = ["Model"]


class Model:
    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else \
            ([inputs] if inputs is not None else None)
        self._amp_level = "O0"
        self._amp_custom = {}
        self._scaler = None
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        """amp_configs (reference model.py:1577): either a level string
        ('O1'/'O2') or a dict {'level', 'custom_white_list',
        'custom_black_list', 'init_loss_scaling', ...} — drives
        amp.auto_cast around every train/eval forward and a GradScaler
        around the backward (O2 additionally decorates the network/optimizer
        to bf16 master weights via amp.decorate)."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics is not None else [])
        if amp_configs is not None:
            from .. import amp as amp_mod
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            level = amp_configs.get("level", "O1")
            if level not in ("O0", "O1", "O2"):
                raise ValueError(f"unsupported amp level {level!r}")
            self._amp_level = level
            self._amp_custom = {
                k: amp_configs[k] for k in
                ("custom_white_list", "custom_black_list") if k in amp_configs}
            if level != "O0":
                scaler_kw = {k: v for k, v in amp_configs.items()
                             if k in ("init_loss_scaling", "incr_ratio",
                                      "decr_ratio", "incr_every_n_steps",
                                      "decr_every_n_nan_or_inf",
                                      "use_dynamic_loss_scaling")}
                self._scaler = amp_mod.GradScaler(**scaler_kw)
            if level == "O2" and optimizer is not None:
                self.network, self._optimizer = amp_mod.decorate(
                    self.network, optimizer, level="O2")
        return self

    def _amp_ctx(self):
        from .. import amp as amp_mod
        if self._amp_level in ("O1", "O2"):
            return amp_mod.auto_cast(True, level=self._amp_level,
                                     **self._amp_custom)
        import contextlib
        return contextlib.nullcontext()

    def _to_loader(self, data, batch_size, shuffle):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle)
        return data

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with self._amp_ctx():
            outputs = self.network(*inputs)
            losses = []
            if self._loss is not None and labels is not None:
                labels_l = labels if isinstance(labels, (list, tuple)) \
                    else [labels]
                loss = self._loss(outputs, *labels_l)
        if self._loss is not None and labels is not None:
            if self._scaler is not None:
                self._scaler.scale(loss).backward()
                if update:
                    self._scaler.step(self._optimizer)
                    self._scaler.update()   # dynamic-scale bookkeeping:
                    # without it an overflow would freeze the scale and
                    # silently skip every subsequent step
                    self._optimizer.clear_grad()
            else:
                loss.backward()
                if update:
                    self._optimizer.step()
                    self._optimizer.clear_grad()
            losses.append(float(loss.numpy()))
        metrics = []
        if labels is not None:
            for m in self._metrics:
                labels_l = labels if isinstance(labels, (list, tuple)) else [labels]
                corr = m.compute(outputs, *labels_l)
                metrics.append(m.update(corr))
        return (losses, metrics) if metrics else losses

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core.dispatch import no_grad
        with no_grad(), self._amp_ctx():
            outputs = self.network(*inputs)
            losses = []
            if self._loss is not None and labels is not None:
                labels_l = labels if isinstance(labels, (list, tuple)) else [labels]
                losses.append(float(self._loss(outputs, *labels_l).numpy()))
            metrics = []
            for m in self._metrics:
                labels_l = labels if isinstance(labels, (list, tuple)) else [labels]
                corr = m.compute(outputs, *labels_l)
                metrics.append(m.update(corr))
        return (losses, metrics) if metrics else losses

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..core.dispatch import no_grad
        with no_grad():
            out = self.network(*inputs)
        return out

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, telemetry=None,
            ckpt=None):
        """``telemetry``: an ``observability.TrainTelemetry`` (or None =
        off).  With one attached, every iteration records its host wall
        time split into data wait (the ``next(loader)`` call) vs compute
        (``train_batch``, whose ``float(loss)`` sync makes it real device
        time) into ``train.step_s`` / ``train.data_s`` /
        ``train.compute_s``, and each ``save_dir`` checkpoint gets a
        ``ckpt.save`` span.  Pure host timing at boundaries the loop
        already crosses: losses are bit-exact telemetry on vs off.

        ``ckpt``: a ``resilience.CheckpointManager`` (or None = off).
        fit() first AUTO-RESUMES from the newest intact snapshot
        (``find_latest_complete()`` — torn snapshots from a crash mid-save
        are skipped), restoring model/optimizer/LR-schedule/scaler/RNG
        bit-exactly and fast-forwarding the data pipeline past the
        restored iteration; then saves a crash-consistent snapshot every
        ``ckpt.save_interval`` iterations.  A preempted fit relaunched
        with the same arguments (and a deterministic batch order —
        ``shuffle=False`` or a seeded loader) continues the loss
        trajectory bit-for-bit; the elastic gang-resume path
        (``hapi.callbacks.ElasticRestart``) stops training on a
        membership change so every surviving rank relaunches from the
        SAME snapshot.  Manager slots left as None (model / optimizer /
        scaler) are attached from this Model."""
        train_loader = self._to_loader(train_data, batch_size, shuffle)
        eval_loader = self._to_loader(eval_data, batch_size, False)
        cbks = CallbackList(callbacks or [ProgBarLogger(log_freq, verbose=verbose)])
        cbks.set_model(self)
        cbks.on_begin("train", {"epochs": epochs,
                                "steps": _safe_len(train_loader),
                                "metrics": self._metric_names()})
        # a previous fit() stopped by a callback (EarlyStopping,
        # ElasticRestart) must not poison this invocation — the elastic
        # relauncher re-invokes fit() on the same Model
        self.stop_training = False
        tel = telemetry
        start_it = 0
        if ckpt is not None:
            if isinstance(train_data, Dataset) and shuffle:
                # the resume fast-forward replays the loader to start_it;
                # an unseeded reshuffle on relaunch would train some
                # samples twice and skip others, silently breaking the
                # bit-exact-trajectory guarantee
                import warnings
                warnings.warn(
                    "fit(ckpt=...) with shuffle=True: auto-resume needs a "
                    "DETERMINISTIC batch order to reproduce the "
                    "uninterrupted trajectory — pass shuffle=False or a "
                    "seeded DataLoader", RuntimeWarning, stacklevel=2)
            if ckpt.model is None:
                ckpt.model = self.network
            if ckpt.optimizer is None:
                ckpt.optimizer = self._optimizer
            if ckpt.scaler is None:
                ckpt.scaler = self._scaler
            start_it = ckpt.restore() or 0
        it = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            data_iter = iter(train_loader)
            step = -1
            while True:
                t_d0 = tel.clock() if tel is not None else 0.0
                try:
                    batch = next(data_iter)
                except StopIteration:
                    break
                if it < start_it:
                    # resume fast-forward: this batch was already trained
                    # (and checkpointed past) before the restart — consume
                    # it from the loader so the data pipeline lines up,
                    # train nothing (the restored RNG/step carry the state)
                    it += 1
                    if num_iters is not None and it >= num_iters:
                        # the snapshot already covers the whole num_iters
                        # budget — training a bonus step here would push
                        # the resumed run PAST the uninterrupted one
                        break
                    continue
                t_d1 = tel.clock() if tel is not None else 0.0
                step += 1
                cbks.on_batch_begin("train", step, logs)
                x, y = self._split_batch(batch)
                res = self.train_batch(x, y)
                if tel is not None:
                    t_c1 = tel.clock()
                    tel.step(t_c1 - t_d0, data_s=t_d1 - t_d0,
                             compute_s=t_c1 - t_d1,
                             samples=batch_samples(x))
                logs = self._pack_logs(res)
                cbks.on_batch_end("train", step, logs)
                it += 1
                if ckpt is not None:
                    ckpt.maybe_save(it)
                if self.stop_training:
                    break
                if num_iters is not None and it >= num_iters:
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size, verbose=0)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                if tel is not None:
                    with tel.span("ckpt.save", epoch=epoch):
                        self.save(f"{save_dir}/epoch_{epoch}")
                    tel.saved(epoch, f"{save_dir}/epoch_{epoch}")
                else:
                    self.save(f"{save_dir}/epoch_{epoch}")
            if self.stop_training or (num_iters is not None and it >= num_iters):
                break
        cbks.on_end("train", logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._to_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        logs = {}
        for step, batch in enumerate(loader):
            x, y = self._split_batch(batch)
            res = self.eval_batch(x, y)
            logs = self._pack_logs(res)
            if num_iters is not None and step + 1 >= num_iters:
                break
        for m in self._metrics:
            logs[m.name()] = m.accumulate()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._to_loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            x, _ = self._split_batch(batch, labeled=False)
            outs.append(self.predict_batch(x))
        return outs

    def save(self, path, training=True):
        """training=True: checkpoint (params + optimizer state).
        training=False: INFERENCE artifact — the StableHLO export via
        jit.save, loadable with paddle.jit.load / the inference Predictor
        (reference model.py:1472 Model.save's save_inference_model branch).
        Requires input specs: pass them at construction
        (Model(net, inputs=[InputSpec(...)])) or infer from static
        metadata."""
        if not training:
            if not self._inputs:
                raise ValueError(
                    "Model.save(training=False) exports an inference "
                    "artifact and needs input specs: construct the Model "
                    "with inputs=[InputSpec(shape, dtype)]")
            from ..jit import save as jit_save
            net = self.network
            inner = getattr(net, "_inner_layer", None)
            jit_save(inner if isinstance(inner, Layer) else net, path,
                     input_spec=list(self._inputs))
            return
        framework.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        sd = framework.load(path + ".pdparams")
        self.network.set_state_dict(sd)
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(framework.load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as s
        return s(self.network, input_size, dtypes=dtype)

    def _split_batch(self, batch, labeled=True):
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return batch[0], batch[1] if labeled else None
        return batch, None

    def _metric_names(self):
        return ["loss"] + [m.name() for m in self._metrics]

    def _pack_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
            if losses:
                logs["loss"] = losses[0]
            for m, v in zip(self._metrics, metrics):
                logs[m.name()] = v
        elif isinstance(res, list) and res:
            logs["loss"] = res[0]
        return logs


def _safe_len(loader):
    try:
        return len(loader)
    except Exception:
        return None
