"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import time

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "ElasticRestart",
           "EarlyStopping", "LRScheduler"]


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, mode, step, logs=None):
        pass

    def on_batch_end(self, mode, step, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, item):
        def call(*args, **kwargs):
            for c in self.callbacks:
                getattr(c, item)(*args, **kwargs)
        return call


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_begin(self, mode, logs=None):
        self._t0 = time.time()
        self._params = logs or {}

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._steps = 0

    def on_batch_end(self, mode, step, logs=None):
        self._steps += 1
        if self.verbose >= 2 and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"Epoch {getattr(self, 'epoch', 0)} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose >= 1:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                               for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = None
        self.wait = 0
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        v = (logs or {}).get(self.monitor)
        if v is None:
            return
        improved = self.best is None or \
            (v < self.best - self.min_delta if self.mode == "min"
             else v > self.best + self.min_delta)
        if improved:
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        lr = getattr(opt, "_learning_rate", None)
        return lr if isinstance(lr, Sched) else None

    def on_batch_end(self, mode, step, logs=None):
        if mode == "train" and self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class ElasticRestart(Callback):
    """The elastic gang-resume glue (ROADMAP smaller item): watch a
    ``distributed.fleet.elastic.ElasticManager`` during ``fit`` and stop
    training at the next batch boundary when gang membership CHANGEs (a
    node joined or left) or drops below ``np_min`` (EXIT).

    The relauncher — ``launch_gang``'s restart hook, or any loop around
    ``fit`` — then re-invokes ``fit(..., ckpt=manager)`` with the SAME
    :class:`~paddle_tpu.resilience.CheckpointManager`: every surviving
    rank auto-resumes from the same ``find_latest_complete()`` snapshot
    (torn snapshots from the preemption are skipped), so the regrouped
    gang continues the loss trajectory bit-exactly from the last durable
    step instead of restarting from zero.  ``status`` holds the
    membership verdict that stopped training (None while stable)."""

    def __init__(self, manager, check_every: int = 1):
        self.manager = manager
        self.check_every = max(1, int(check_every))
        self.status = None
        self._n = 0

    def on_batch_end(self, mode, step, logs=None):
        if mode != "train":
            return
        self._n += 1
        if self._n % self.check_every:
            return
        from ..distributed.fleet.elastic import ElasticStatus
        st = self.manager.watch()
        if st != ElasticStatus.HOLD:
            self.status = st
            self.model.stop_training = True
