"""paddle.flops (reference: python/paddle/hapi/dynamic_flops.py:40) —
per-layer FLOP/parameter counting via forward hooks on a probe pass."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..nn import (Conv2D, Linear, BatchNorm2D, BatchNorm1D, LayerNorm,
                  ReLU, AvgPool2D, MaxPool2D, AdaptiveAvgPool2D)

__all__ = ["flops"]


def _numel(shape):
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _count_conv2d(layer, x, y):
    kh, kw = layer._kernel_size if isinstance(layer._kernel_size, (list, tuple)) \
        else (layer._kernel_size, layer._kernel_size)
    cin = layer._in_channels
    groups = getattr(layer, "_groups", 1)
    out_elems = _numel(y.shape)
    macs = out_elems * cin // groups * kh * kw
    if getattr(layer, "bias", None) is not None:
        macs += out_elems
    return macs


def _count_linear(layer, x, y):
    macs = _numel(y.shape) * layer._in_features
    if getattr(layer, "bias", None) is not None:
        macs += _numel(y.shape)
    return macs


def _count_norm(layer, x, y):
    return 2 * _numel(x.shape)


def _count_act(layer, x, y):
    return _numel(y.shape)


def _count_pool(layer, x, y):
    return _numel(y.shape)


_COUNTERS = [
    (Conv2D, _count_conv2d),
    (Linear, _count_linear),
    (BatchNorm2D, _count_norm), (BatchNorm1D, _count_norm),
    (LayerNorm, _count_norm),
    (ReLU, _count_act),
    (AvgPool2D, _count_pool), (MaxPool2D, _count_pool),
    (AdaptiveAvgPool2D, _count_pool),
]


def flops(net: Layer, input_size, custom_ops=None, print_detail=False):
    """Probe-run `net` on zeros of `input_size` and report total FLOPs
    (counted as MACs, matching the reference convention). custom_ops:
    {LayerType: fn(layer, input, output) -> macs}."""
    custom = dict(custom_ops or {})
    rows = []
    handles = []

    def make_hook(counter):
        def hook(layer, inputs, output):
            x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            macs = counter(layer, x, output)
            n_params = sum(_numel(p.shape) for _, p in
                           layer.named_parameters(include_sublayers=False))
            rows.append((type(layer).__name__, tuple(x.shape),
                         tuple(output.shape), n_params, int(macs)))
        return hook

    for sub in net.sublayers(include_self=True):
        counter = custom.get(type(sub))
        if counter is None:
            for cls, fn in _COUNTERS:
                if type(sub) is cls:
                    counter = fn
                    break
        if counter is not None:
            handles.append(sub.register_forward_post_hook(make_hook(counter)))

    was_training = getattr(net, "training", False)
    net.eval()
    try:
        x = Tensor(np.zeros(tuple(input_size), np.float32))
        net(x)
    finally:
        for h in handles:
            if hasattr(h, "remove"):
                h.remove()
        if was_training:
            net.train()

    total = sum(r[4] for r in rows)
    total_params = sum(r[3] for r in rows)
    if print_detail:
        print(f"{'Layer':<20}{'Input':<20}{'Output':<20}"
              f"{'Params':>12}{'FLOPs':>16}")
        for name, ishape, oshape, n_params, macs in rows:
            print(f"{name:<20}{str(ishape):<20}{str(oshape):<20}"
                  f"{n_params:>12}{macs:>16}")
        print(f"Total params: {total_params}  Total FLOPs: {total}")
    return total
