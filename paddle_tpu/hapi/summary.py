"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total_params = 0
    trainable_params = 0
    for name, p in net.named_parameters():
        n = p.size
        total_params += n
        if p.trainable:
            trainable_params += n
        rows.append((name, tuple(p.shape), n))
    width = max([len(r[0]) for r in rows], default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':>12}",
             "=" * (width + 32)]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:>12,}")
    lines.append("=" * (width + 32))
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    lines.append(f"Non-trainable params: {total_params - trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable_params}
