"""Random ops (reference: python/paddle/tensor/random.py).

Counter-based jax.random keys drawn from the default Generator replace the
reference's Philox seed/offset state (paddle/phi/core/generator.h:32).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import dtype as dtype_mod
from ..core.random import split_key

__all__ = [
    "rand", "randn", "normal", "uniform", "randint", "randint_like", "randperm",
    "bernoulli", "multinomial", "poisson", "standard_normal", "standard_gamma",
    "exponential_", "uniform_", "normal_", "binomial", "log_normal",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(shape.numpy().tolist())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype):
    d = dtype_mod.convert_dtype(dtype)
    return d if d is not None else dtype_mod.default_float_dtype()


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(split_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(split_key(), _shape(shape), _dt(dtype)))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = np.broadcast_shapes(np.shape(m), np.shape(s))
        return Tensor(jax.random.normal(split_key(), shp, dtype_mod.default_float_dtype()) * s + m)
    shp = _shape(shape if shape is not None else [1])
    return Tensor(jax.random.normal(split_key(), shp, dtype_mod.default_float_dtype()) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None):
    return x._set_value(jax.random.normal(split_key(), tuple(x.shape), x._value.dtype) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else split_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype), min, max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else split_key()
    return x._set_value(jax.random.uniform(key, tuple(x.shape), x._value.dtype, min, max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(split_key(), _shape(shape), low, high,
                                     dtype=dtype_mod.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = dtype_mod.convert_dtype(dtype) or x._value.dtype
    out = jax.random.randint(split_key(), tuple(x.shape), low, high, dtype=jnp.int32)
    return Tensor(out.astype(d))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(split_key(), n).astype(dtype_mod.convert_dtype(dtype)))


def bernoulli(x, name=None):
    p = x._value
    return Tensor(jax.random.bernoulli(split_key(), p).astype(p.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    v = x._value
    logits = jnp.log(jnp.clip(v, 1e-30, None))
    if v.ndim == 1:
        out = jax.random.choice(split_key(), v.shape[0], (num_samples,),
                                replace=replacement, p=v / v.sum())
        return Tensor(out.astype(jnp.int64))
    keys = jax.random.split(split_key(), v.shape[0])
    def one(k, row):
        return jax.random.choice(k, v.shape[1], (num_samples,), replace=replacement,
                                 p=row / row.sum())
    return Tensor(jax.vmap(one)(keys, v).astype(jnp.int64))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(split_key(), x._value).astype(x._value.dtype))


def binomial(count, prob, name=None):
    c = count._value if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._value if isinstance(prob, Tensor) else jnp.asarray(prob)
    return Tensor(jax.random.binomial(split_key(), c.astype(jnp.float32), p).astype(jnp.int64))


def standard_gamma(alpha, name=None):
    a = alpha._value if isinstance(alpha, Tensor) else jnp.asarray(alpha)
    return Tensor(jax.random.gamma(split_key(), a))


def exponential_(x, lam=1.0, name=None):
    return x._set_value(jax.random.exponential(split_key(), tuple(x.shape),
                                               x._value.dtype) / lam)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    shp = _shape(shape if shape is not None else [1])
    return Tensor(jnp.exp(jax.random.normal(split_key(), shp) * std + mean))
