"""Search / sort ops (reference: python/paddle/tensor/search.py).

Differentiable values are produced via argsort + take_along_axis so that
integer-output ops stay out of the vjp path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "searchsorted", "nonzero",
    "index_sample", "masked_select", "where", "kthvalue", "mode", "median",
    "nanmedian", "quantile", "nanquantile", "bincount", "histogram_bin_edges",
]

from .manipulation import masked_select, where, nonzero  # re-export


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def impl(v):
        if axis is None:
            out = jnp.argmax(v.reshape(-1))
            return out.reshape((1,) * v.ndim).squeeze() if not keepdim else out.reshape((1,) * v.ndim)
        return jnp.argmax(v, axis=axis, keepdims=keepdim).astype(d)
    return op_call("argmax", impl, x, nondiff=True)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def impl(v):
        if axis is None:
            return jnp.argmin(v.reshape(-1)).astype(d)
        return jnp.argmin(v, axis=axis, keepdims=keepdim).astype(d)
    return op_call("argmin", impl, x, nondiff=True)


def argsort(x, axis=-1, descending=False, stable=True, name=None):
    def impl(v):
        idx = jnp.argsort(v, axis=axis, stable=stable, descending=descending)
        return idx.astype(jnp.int64)
    return op_call("argsort", impl, x, nondiff=True)


def sort(x, axis=-1, descending=False, stable=True, name=None):
    idx = argsort(x, axis=axis, descending=descending, stable=stable)
    from .manipulation import take_along_axis
    return take_along_axis(x, idx, axis=axis)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    kk = int(k._value) if isinstance(k, Tensor) else int(k)
    def impl_idx(v):
        ax = axis % v.ndim
        vv = jnp.moveaxis(v, ax, -1)
        if largest:
            _, idx = jax.lax.top_k(vv, kk)
        else:
            _, idx = jax.lax.top_k(-vv, kk)
        return jnp.moveaxis(idx, -1, ax).astype(jnp.int64)
    indices = op_call("topk_idx", impl_idx, x, nondiff=True)
    from .manipulation import take_along_axis
    values = take_along_axis(x, indices, axis=axis)
    return values, indices


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else jnp.int64
    def impl(s, v):
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side).astype(d)
        flat_s = s.reshape(-1, s.shape[-1])
        flat_v = v.reshape(-1, v.shape[-1])
        out = jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(flat_s, flat_v)
        return out.reshape(v.shape).astype(d)
    return op_call("searchsorted", impl, sorted_sequence, values, nondiff=True)


def index_sample(x, index, name=None):
    from .manipulation import take_along_axis
    return take_along_axis(x, index, axis=1)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def impl_idx(v):
        idx = jnp.argsort(v, axis=axis)
        sel = jnp.take(idx, k - 1, axis=axis)
        return jnp.expand_dims(sel, axis).astype(jnp.int64)
    indices = op_call("kthvalue_idx", impl_idx, x, nondiff=True)
    from .manipulation import take_along_axis, squeeze
    values = take_along_axis(x, indices, axis=axis)
    if not keepdim:
        values = squeeze(values, axis)
        indices = squeeze(indices, axis)
    return values, indices


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along `axis` -> (values, indices); indices are
    the LAST position of the modal value (torch/paddle convention).  Fully
    traceable: sort + pairwise-equality counts (O(n^2) on the axis) instead
    of the host scipy call the pre-round-5 version used — which also
    returned counts where the API promises indices."""
    def impl_vals(v):
        m = jnp.moveaxis(v, axis, -1)
        s = jnp.sort(m, axis=-1)
        n = s.shape[-1]
        idx = jnp.arange(n)
        # run-length counts in O(n): for each sorted position, the first and
        # last index of its equal-value run via cummax tricks (no [n, n]
        # pairwise tensor — that was a 40 GB cliff at n=100k)
        new_run = jnp.concatenate(
            [jnp.ones_like(s[..., :1], bool), s[..., 1:] != s[..., :-1]], -1)
        first = jax.lax.cummax(jnp.where(new_run, idx, 0), axis=s.ndim - 1)
        run_end = jnp.concatenate(
            [s[..., 1:] != s[..., :-1], jnp.ones_like(s[..., :1], bool)], -1)
        last = (n - 1) - jnp.flip(jax.lax.cummax(
            jnp.flip(jnp.where(run_end, (n - 1) - idx, 0), -1),
            axis=s.ndim - 1), -1)
        counts = last - first + 1
        # argmax picks the FIRST max in sorted order -> smallest modal value
        best = jnp.argmax(counts, axis=-1)
        vals = jnp.take_along_axis(s, best[..., None], -1)[..., 0]
        return jnp.expand_dims(vals, axis) if keepdim else vals

    values = op_call("mode_values", impl_vals, x)

    def impl_idx(v, vals):
        m = jnp.moveaxis(v, axis, -1)
        mv = jnp.moveaxis(vals, axis, -1)[..., 0] if keepdim else vals
        n = m.shape[-1]
        eq = m == mv[..., None]
        idx = (n - 1) - jnp.argmax(jnp.flip(eq, -1), axis=-1)
        if keepdim:
            idx = jnp.expand_dims(idx, axis)
        return idx.astype(jnp.int64)

    indices = op_call("mode_indices", impl_idx, x, values, nondiff=True)
    return values, indices


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def impl(v):
        if mode == "avg":
            return jnp.median(v, axis=axis, keepdims=keepdim)
        n = v.shape[axis] if axis is not None else v.size
        srt = jnp.sort(v.reshape(-1) if axis is None else v, axis=0 if axis is None else axis)
        mid = (n - 1) // 2
        return jnp.take(srt, mid, axis=0 if axis is None else axis)
    return op_call("median", impl, x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return op_call("nanmedian", lambda v: jnp.nanmedian(v, axis=axis, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return op_call("quantile",
                   lambda v: jnp.quantile(v, qq, axis=axis, keepdims=keepdim,
                                          method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return op_call("nanquantile",
                   lambda v: jnp.nanquantile(v, qq, axis=axis, keepdims=keepdim,
                                             method=interpolation), x)


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        return op_call("bincount",
                       lambda v, w: jnp.bincount(v, weights=w, minlength=minlength,
                                                 length=max(int(np.asarray(v).max(initial=0)) + 1, minlength, 1)),
                       x, weights, nondiff=True)
    v = np.asarray(x._value)
    length = max(int(v.max(initial=0)) + 1, minlength, 1)
    return op_call("bincount", lambda t: jnp.bincount(t, minlength=minlength, length=length),
                   x, nondiff=True)


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    """Traceable when (min, max) are given; the data-dependent range (both
    zero, numpy semantics) needs a concrete input (host reduction)."""
    def impl(v):
        if min == 0 and max == 0:
            if isinstance(v, jax.core.Tracer):
                raise ValueError(
                    "histogram_bin_edges under jit needs explicit "
                    "(min, max) — the data range is a host-side reduction")
            lo, hi = float(jnp.min(v)), float(jnp.max(v))
        else:
            lo, hi = float(min), float(max)
        if lo == hi:
            lo, hi = lo - 0.5, hi + 0.5   # numpy's zero-width expansion
        return jnp.linspace(lo, hi, int(bins) + 1, dtype=jnp.float32)
    return op_call("histogram_bin_edges", impl, x, nondiff=True)


def _top_p_mask(v, p):
    """Nucleus mask over the last axis: keep the smallest set of
    highest-probability entries whose cumulative probability reaches `p`
    (always at least the argmax); everything else -> -inf.  `p` may be a
    python scalar or a per-row array broadcastable to v.shape[:-1] — the
    per-row form is what the paged serving engine's per-request sampling
    rides (inference/paged.py)."""
    pb = jnp.broadcast_to(jnp.asarray(p, jnp.float32), v.shape[:-1])
    sorted_logits = jnp.sort(v, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_n = jnp.sum(cum < pb[..., None], axis=-1)
    cutoff = jnp.take_along_axis(sorted_logits, keep_n[..., None], -1)
    return jnp.where(v < cutoff, -jnp.inf, v)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over the last axis (reference top_p_sampling):
    returns (sampled values [..., 1], sampled ids [..., 1]), one draw per
    row — column tensors, matching the reference's shape=[B, 1] contract
    (ADVICE r5 #1)."""
    from ..core.random import split_key

    key = split_key() if seed is None else jax.random.PRNGKey(int(seed))

    def impl(v, p, *rest):
        masked = _top_p_mask(v, p)
        if rest:
            # reference threshold: a per-row probability floor that further
            # restricts the nucleus (keep at least the argmax)
            full_probs = jax.nn.softmax(v, axis=-1)
            floor = rest[0].reshape(v.shape[:-1] + (1,))
            below = full_probs < floor
            best = jnp.argmax(v, axis=-1, keepdims=True)
            below = below & ~(jnp.arange(v.shape[-1]) == best)
            masked = jnp.where(below, -jnp.inf, masked)
        ids = jax.random.categorical(key, masked, axis=-1)
        vals = jnp.take_along_axis(v, ids[..., None], -1)
        return vals, ids[..., None].astype(jnp.int64)
    args = (x, ps) if threshold is None else (x, ps, threshold)
    return op_call("top_p_sampling", impl, *args, nondiff=True)


__all__ += ["top_p_sampling"]
