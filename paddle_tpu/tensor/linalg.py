"""Linear algebra ops (reference: python/paddle/tensor/linalg.py; matmul at
linalg.py:220). matmul/einsum hit the MXU; decompositions route to
jax.numpy.linalg (XLA custom calls).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = [
    "matmul", "mm", "bmm", "mv", "norm", "vector_norm", "matrix_norm", "dist",
    "cholesky", "cholesky_solve", "qr", "svd", "svdvals", "inv", "solve",
    "lstsq", "det", "slogdet", "pinv", "matrix_power", "matrix_rank", "eig",
    "eigh", "eigvals", "eigvalsh", "lu", "lu_unpack", "triangular_solve",
    "multi_dot", "einsum", "cov", "corrcoef", "histogram", "histogramdd",
    "cdist", "pdist", "householder_product", "pca_lowrank", "matrix_exp", "ormqr",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def impl(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return op_call("matmul", impl, x, y)


def mm(input, mat2, name=None):
    return op_call("matmul", jnp.matmul, input, mat2)


def bmm(x, y, name=None):
    return op_call("bmm", jnp.matmul, x, y)


def mv(x, vec, name=None):
    return op_call("mv", jnp.matmul, x, vec)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def impl(v):
        pp = p
        if axis is None and pp is None:
            return jnp.linalg.norm(v.reshape(-1))
        if axis is None:
            return jnp.linalg.norm(v.reshape(-1), ord=None if pp == "fro" else pp)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if pp is None:
            pp = "fro" if isinstance(ax, tuple) else 2
        if isinstance(ax, tuple) and len(ax) == 1:
            ax = ax[0]
        return jnp.linalg.norm(v, ord=pp, axis=ax, keepdims=keepdim)
    return op_call("norm", impl, x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    def impl(v):
        if ax is None:
            return jnp.linalg.norm(v.reshape(-1), ord=p)
        return jnp.linalg.norm(v, ord=p, axis=ax, keepdims=keepdim)
    return op_call("vector_norm", impl, x)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return op_call("matrix_norm",
                   lambda v: jnp.linalg.norm(v, ord=p, axis=tuple(axis), keepdims=keepdim), x)


def dist(x, y, p=2, name=None):
    def impl(a, b):
        d = (a - b).reshape(-1)
        if p == 0:
            return jnp.count_nonzero(d).astype(a.dtype)
        if np.isinf(p):
            return jnp.max(jnp.abs(d)) if p > 0 else jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return op_call("dist", impl, x, y)


def cholesky(x, upper=False, name=None):
    def impl(v):
        L = jnp.linalg.cholesky(v)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return op_call("cholesky", impl, x)


def cholesky_solve(x, y, upper=False, name=None):
    def impl(b, chol):
        return jax.scipy.linalg.cho_solve((chol, not upper), b)
    return op_call("cholesky_solve", impl, x, y)


def qr(x, mode="reduced", name=None):
    outs = op_call("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), x) \
        if mode != "r" else (op_call("qr_r", lambda v: jnp.linalg.qr(v, mode="r"), x),)
    return outs if len(outs) > 1 else outs[0]


def svd(x, full_matrices=False, name=None):
    return op_call("svd", lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), x)


def svdvals(x, name=None):
    return op_call("svdvals", lambda v: jnp.linalg.svd(v, compute_uv=False), x)


def inv(x, name=None):
    return op_call("inv", jnp.linalg.inv, x)


def solve(x, y, name=None):
    return op_call("solve", jnp.linalg.solve, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def impl(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return op_call("lstsq", impl, x, y)


def det(x, name=None):
    return op_call("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    def impl(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])
    return op_call("slogdet", impl, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return op_call("pinv", lambda v: jnp.linalg.pinv(v, rcond=rcond, hermitian=hermitian), x)


def matrix_power(x, n, name=None):
    return op_call("matrix_power", lambda v: jnp.linalg.matrix_power(v, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return op_call("matrix_rank",
                   lambda v: jnp.linalg.matrix_rank(v, tol=tol),
                   x, nondiff=True)


def matrix_exp(x, name=None):
    return op_call("matrix_exp", jax.scipy.linalg.expm, x)


def eig(x, name=None):
    # CPU-only in XLA: route via host numpy for parity (reference supports it
    # only on CPU-backed LAPACK too)
    v = np.asarray(x._value)
    w, vecs = np.linalg.eig(v)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(vecs))


def eigvals(x, name=None):
    v = np.asarray(x._value)
    return Tensor(jnp.asarray(np.linalg.eigvals(v)))


def eigh(x, UPLO="L", name=None):
    return op_call("eigh", lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), x)


def eigvalsh(x, UPLO="L", name=None):
    return op_call("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)


def lu(x, pivot=True, get_infos=False, name=None):
    def impl(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, piv.astype(jnp.int32) + 1  # paddle returns 1-based pivots
    lu_t, piv = op_call("lu", impl, x)
    if get_infos:
        return lu_t, piv, Tensor(jnp.zeros(x.shape[:-2], jnp.int32))
    return lu_t, piv


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    def impl(lu_v, piv):
        m, n = lu_v.shape[-2], lu_v.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_v[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_v.dtype)
        U = jnp.triu(lu_v[..., :k, :])
        # build permutation from 1-based pivots
        piv0 = piv.astype(jnp.int32) - 1
        def perm_one(pv):
            perm = jnp.arange(m)
            def body(i, perm):
                j = pv[i]
                a, b = perm[i], perm[j]
                perm = perm.at[i].set(b).at[j].set(a)
                return perm
            return jax.lax.fori_loop(0, pv.shape[0], body, perm)
        if piv0.ndim == 1:
            perm = perm_one(piv0)
            P = jnp.eye(m, dtype=lu_v.dtype)[perm].T
        else:
            flatp = piv0.reshape(-1, piv0.shape[-1])
            perms = jax.vmap(perm_one)(flatp)
            P = jax.vmap(lambda p: jnp.eye(m, dtype=lu_v.dtype)[p].T)(perms)
            P = P.reshape(lu_v.shape[:-2] + (m, m))
        return P, L, U
    return op_call("lu_unpack", impl, lu_data, lu_pivots)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def impl(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return op_call("triangular_solve", impl, x, y)


def multi_dot(tensors, name=None):
    return op_call("multi_dot", lambda *vs: jnp.linalg.multi_dot(list(vs)), *tensors)


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return op_call("einsum", lambda *vs: jnp.einsum(equation, *vs), *operands)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = fweights._value if isinstance(fweights, Tensor) else fweights
    aw = aweights._value if isinstance(aweights, Tensor) else aweights
    return op_call("cov", lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0,
                                            fweights=fw, aweights=aw), x)


def corrcoef(x, rowvar=True, name=None):
    return op_call("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    """Traceable histogram (the pre-round-5 version round-tripped through
    host numpy and broke under jit).  With min == max == 0 the range comes
    from the data (numpy semantics), which requires a concrete input —
    eager falls back to value-dependent bounds, traced raises like the
    reference's shape-inference would."""
    def impl(v, *rest):
        w = rest[0] if rest else None
        if min == 0 and max == 0:
            if isinstance(v, jax.core.Tracer):
                raise ValueError(
                    "histogram under jit needs explicit (min, max) — the "
                    "data-dependent range is a host-side reduction")
            lo, hi = float(jnp.min(v)), float(jnp.max(v))
        else:
            lo, hi = float(min), float(max)
        hist, _ = jnp.histogram(v.reshape(-1), bins=bins, range=(lo, hi),
                                weights=None if w is None else w.reshape(-1),
                                density=density)
        if density or w is not None:
            return hist
        return hist.astype(jnp.int64)
    args = (input,) if weight is None else (input, weight)
    return op_call("histogram", impl, *args, nondiff=True)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    v = np.asarray(x._value)
    w = np.asarray(weights._value) if weights is not None else None
    if ranges is not None:
        # paddle passes a FLAT [lo0, hi0, lo1, hi1, ...] list (reference
        # linalg.py histogramdd); numpy wants per-dimension pairs
        flat = list(ranges)
        ranges = [(flat[2 * i], flat[2 * i + 1]) for i in range(len(flat) // 2)]
    hist, edges = np.histogramdd(v, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def impl(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    return op_call("cdist", impl, x, y)


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distance of the rows of x [N, D] -> [N·(N-1)/2]
    (reference python/paddle/tensor/linalg.py pdist; scipy.spatial.distance
    .pdist ordering: (0,1), (0,2), ..., (N-2,N-1)).

    p=2 uses the Gram-matrix identity |x_i - x_j|^2 = |x_i|^2 + |x_j|^2 -
    2 x_i·x_j — one MXU matmul and an [N, N] intermediate instead of the
    [N(N-1)/2, D] gathered-diff tensor (D× less memory); identical rows
    yield exactly 0."""
    n = int(x.shape[0])
    iu, ju = np.triu_indices(n, k=1)

    def impl(a):
        if p == 2.0:
            sq = jnp.sum(a * a, axis=-1)
            d2 = sq[:, None] + sq[None, :] - 2.0 * (a @ a.T)
            d2 = jnp.maximum(d2[iu, ju], 0.0)
            # grad-safe sqrt: exactly 0 (with zero grad) at coincident rows
            safe = jnp.where(d2 > 0.0, d2, 1.0)
            return jnp.where(d2 > 0.0, jnp.sqrt(safe), 0.0)
        diff = a[iu] - a[ju]
        if p == 0.0:
            return jnp.sum((diff != 0).astype(a.dtype), axis=-1)
        if p == float("inf"):
            return jnp.max(jnp.abs(diff), axis=-1)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    return op_call("pdist", impl, x)


def householder_product(x, tau, name=None):
    def impl(a, t):
        m, n = a.shape[-2], a.shape[-1]
        def one(av, tv):
            Q = jnp.eye(m, dtype=av.dtype)
            def body(i, Q):
                v = jnp.where(jnp.arange(m) < i, 0.0, av[:, i])
                v = v.at[i].set(1.0)
                H = jnp.eye(m, dtype=av.dtype) - tv[i] * jnp.outer(v, v)
                return Q @ H
            Q = jax.lax.fori_loop(0, tv.shape[0], body, Q)
            return Q[:, :n]
        if a.ndim == 2:
            return one(a, t)
        flat_a = a.reshape((-1,) + a.shape[-2:])
        flat_t = t.reshape((-1,) + t.shape[-1:])
        out = jax.vmap(one)(flat_a, flat_t)
        return out.reshape(a.shape[:-2] + out.shape[-2:])
    return op_call("householder_product", impl, x, tau)


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    Q = householder_product(x, tau)
    def impl(q, other):
        qq = jnp.swapaxes(q, -1, -2) if transpose else q
        return jnp.matmul(qq, other) if left else jnp.matmul(other, qq)
    return op_call("ormqr", impl, Q, y)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def impl(v):
        vv = v - jnp.mean(v, axis=-2, keepdims=True) if center else v
        u, s, vt = jnp.linalg.svd(vv, full_matrices=False)
        k = q if q is not None else min(6, v.shape[-1])
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]
    return op_call("pca_lowrank", impl, x)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    """reference linalg.py trace."""
    return op_call("trace", lambda v: jnp.trace(v, offset=offset, axis1=axis1,
                                                axis2=axis2), x)


def inverse(x, name=None):
    """alias of inv (reference linalg.py inverse)."""
    return inv(x)


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A from its Cholesky factor (reference cholesky_inverse)."""
    def impl(L):
        eye = jnp.eye(L.shape[-1], dtype=L.dtype)
        return jax.scipy.linalg.cho_solve((L, not upper), eye)
    return op_call("cholesky_inverse", impl, x)


def matrix_transpose(x, name=None):
    """Swap the last two dims (reference linalg.py matrix_transpose)."""
    return op_call("matrix_transpose",
                   lambda v: jnp.swapaxes(v, -1, -2), x)


def cond(x, p=None, name=None):
    """Matrix condition number (reference linalg.py cond)."""
    def impl(v):
        pp = 2 if p is None else p
        if pp in (2, -2):
            s = jnp.linalg.svd(v, compute_uv=False)
            return s[..., 0] / s[..., -1] if pp == 2 else s[..., -1] / s[..., 0]
        return jnp.linalg.norm(v, ord=pp, axis=(-2, -1)) * \
            jnp.linalg.norm(jnp.linalg.inv(v), ord=pp, axis=(-2, -1))
    return op_call("cond", impl, x, nondiff=True)


def block_diag(inputs, name=None):
    """Block-diagonal matrix from a list of 2-D tensors (reference
    block_diag)."""
    ts = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
          for t in inputs]

    def impl(*vals):
        return jax.scipy.linalg.block_diag(*[jnp.atleast_2d(v)
                                             for v in vals])
    return op_call("block_diag", impl, *ts)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference linalg.py svd_lowrank): subspace
    iteration on a fixed-seed Gaussian sketch — MXU-friendly (QR + matmuls),
    rank-q factors for an [m, n] input."""
    def impl(v, *rest):
        if rest:
            v = v - rest[0]          # centered/PCA variant (reference M)
        m, n = v.shape[-2], v.shape[-1]
        k = min(q, m, n)
        key = jax.random.PRNGKey(0)
        omega = jax.random.normal(key, v.shape[:-2] + (n, k), v.dtype)
        y = v @ omega
        for _ in range(niter):
            y = v @ (jnp.swapaxes(v, -1, -2) @ y)
        Q, _ = jnp.linalg.qr(y)
        B = jnp.swapaxes(Q, -1, -2) @ v
        u_b, s, vt = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u_b, s, jnp.swapaxes(vt, -1, -2)
    args = (x,) if M is None else (x, M)
    return op_call("svd_lowrank", impl, *args, nondiff=True)


__all__ += ["trace", "inverse", "cholesky_inverse", "matrix_transpose",
            "cond", "block_diag", "svd_lowrank"]
