"""Comparison / logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not", "logical_xor",
    "isclose", "allclose", "equal_all", "is_empty", "isreal", "iscomplex",
    "isposinf", "isneginf", "is_floating_point", "is_integer", "is_complex",
]


def _cmp(name, fn):
    def op(x, y, name=None):
        return op_call(name, fn, x, y, nondiff=True)
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)


def logical_not(x, out=None, name=None):
    return op_call("logical_not", jnp.logical_not, x, nondiff=True)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return op_call("isclose",
                   lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                   x, y, nondiff=True)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return op_call("allclose",
                   lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
                   x, y, nondiff=True)


def equal_all(x, y, name=None):
    return op_call("equal_all", lambda a, b: jnp.array_equal(a, b), x, y, nondiff=True)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def isreal(x, name=None):
    return op_call("isreal", jnp.isreal, x, nondiff=True)


def iscomplex(x, name=None):
    return Tensor(jnp.asarray(jnp.issubdtype(x._value.dtype, jnp.complexfloating)))


def isposinf(x, name=None):
    return op_call("isposinf", jnp.isposinf, x, nondiff=True)


def isneginf(x, name=None):
    return op_call("isneginf", jnp.isneginf, x, nondiff=True)


def is_floating_point(x):
    return jnp.issubdtype(x._value.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x._value.dtype, jnp.integer)


def is_complex(x):
    return jnp.issubdtype(x._value.dtype, jnp.complexfloating)


def all(x, axis=None, keepdim=False, name=None):
    """reference logic.py all (boolean reduction)."""
    return op_call("all", lambda v: jnp.all(v, axis=axis, keepdims=keepdim),
                   x, nondiff=True)


def any(x, axis=None, keepdim=False, name=None):
    """reference logic.py any."""
    return op_call("any", lambda v: jnp.any(v, axis=axis, keepdims=keepdim),
                   x, nondiff=True)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    """reference logic.py isin (membership against test_x's elements)."""
    def impl(v, t):
        out = jnp.isin(v, t, assume_unique=assume_unique)
        return ~out if invert else out
    return op_call("isin", impl, x, test_x, nondiff=True)


def signbit(x, name=None):
    """reference math.py signbit (true where the sign bit is set)."""
    return op_call("signbit", jnp.signbit, x, nondiff=True)


def less(x, y, name=None):
    """alias of less_than (reference logic.py less)."""
    return less_than(x, y)


__all__ += ["all", "any", "isin", "signbit", "less"]
