"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..core.dispatch import op_call
from ..core import dtype as dtype_mod
from ..core import random as random_mod

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "diag", "diagflat", "tril", "triu", "meshgrid", "assign",
    "clone", "tril_indices", "triu_indices", "complex", "polar",
    "create_parameter", "diag_embed",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype, default=None):
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        d = default if default is not None else dtype_mod.default_float_dtype()
    return d


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value._value
    if dtype is None:
        v = jnp.full(_shape(shape), fill_value)
        if jnp.issubdtype(v.dtype, jnp.floating):
            v = v.astype(dtype_mod.default_float_dtype())
    else:
        v = jnp.full(_shape(shape), fill_value, _dt(dtype))
    return Tensor(v)


def zeros_like(x, dtype=None, name=None):
    return op_call("zeros_like", lambda v: jnp.zeros_like(v, dtype=dtype_mod.convert_dtype(dtype)), x, nondiff=True)


def ones_like(x, dtype=None, name=None):
    return op_call("ones_like", lambda v: jnp.ones_like(v, dtype=dtype_mod.convert_dtype(dtype)), x, nondiff=True)


def full_like(x, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value._value
    return op_call("full_like", lambda v: jnp.full_like(v, fill_value, dtype=dtype_mod.convert_dtype(dtype)), x, nondiff=True)


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v._value.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        if any(isinstance(v, float) for v in (start, end, step)):
            d = dtype_mod.default_float_dtype()
        else:
            d = np.dtype("int64")
    return Tensor(jnp.arange(start, end, step, dtype=d))


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v._value.item() if isinstance(v, Tensor) else v
    d = _dt(dtype)
    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)), dtype=d))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v._value.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.logspace(val(start), val(stop), int(val(num)), base=val(base),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns),
                          dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def impl(v):
        if v.ndim == 1:
            out = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(v, dtype=bool), k=offset)
                out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
            return out
        return jnp.diagonal(v, offset=offset)
    return op_call("diag", impl, x)


def diagflat(x, offset=0, name=None):
    return op_call("diagflat", lambda v: jnp.diagflat(v, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def impl(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = base.at[..., r, c].set(v)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
            order = list(range(nd - 2))
            full_perm = []
            src = {d1: nd - 2, d2: nd - 1}
            rest = iter(order)
            for i in range(nd):
                if i == d1:
                    full_perm.append(nd - 2)
                elif i == d2:
                    full_perm.append(nd - 1)
                else:
                    full_perm.append(next(rest))
            out = jnp.transpose(out, full_perm)
        return out
    return op_call("diag_embed", impl, x)


def tril(x, diagonal=0, name=None):
    return op_call("tril", lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return op_call("triu", lambda v: jnp.triu(v, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, np.dtype("int64"))))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype, np.dtype("int64"))))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    outs = jnp.meshgrid(*vals, indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    """Copy op — differentiable (grad of a copy is identity), so it must go
    through op_call; the pre-round-5 bare Tensor(v) silently detached the
    result from the tape."""
    from ..core.dispatch import op_call
    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    out = op_call("assign", lambda v: v, x)
    if output is not None:
        output._set_value(out._value)
        return output
    return out


def clone(x, name=None):
    return op_call("clone", lambda v: v + jnp.zeros((), v.dtype) if jnp.issubdtype(v.dtype, jnp.inexact) else jnp.array(v), x)


def complex(real, imag, name=None):
    return op_call("complex", lambda r, i: jax.lax.complex(r, i), real, imag)


def polar(abs_t, angle, name=None):
    return op_call("polar", lambda a, th: jax.lax.complex(a * jnp.cos(th), a * jnp.sin(th)),
                   abs_t, angle)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..core.tensor import Parameter
    d = _dt(dtype)
    key = random_mod.split_key()
    if default_initializer is not None:
        t = Parameter(jnp.zeros(_shape(shape), d), name=name)
        default_initializer(t)
        return t
    if is_bias:
        v = jnp.zeros(_shape(shape), d)
    else:
        # Xavier/Glorot uniform default, matching reference create_parameter
        shp = _shape(shape)
        fan_in = shp[0] if shp else 1
        fan_out = shp[1] if len(shp) > 1 else 1
        limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
        v = jax.random.uniform(key, shp, d, -limit, limit)
    return Parameter(v, name=name)
