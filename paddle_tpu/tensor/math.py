"""Math ops (reference: python/paddle/tensor/math.py, ops.yaml entries).

All ops funnel through op_call dispatch (kernel-override capable) onto
jax.numpy/lax impls, which XLA fuses on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..core.dispatch import op_call
from ..core import dtype as dtype_mod

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "sqrt", "rsqrt", "exp", "expm1", "log", "log2", "log10", "log1p",
    "abs", "neg", "sign", "floor", "ceil", "round", "trunc", "frac",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "deg2rad", "rad2deg",
    "clip", "maximum", "minimum", "fmax", "fmin", "reciprocal", "square",
    "lerp", "erf", "erfinv", "logit", "logaddexp", "hypot",
    "isnan", "isinf", "isfinite", "nan_to_num", "nansum", "nanmean",
    "sum", "mean", "max", "min", "amax", "amin", "prod", "logsumexp",
    "cumsum", "cumprod", "cummax", "cummin", "count_nonzero",
    "multiply_", "add_", "subtract_", "scale", "scale_", "increment",
    "stanh", "softplus_math", "addmm", "outer", "inner", "cross", "dot",
    "gcd", "lcm", "heaviside", "digamma", "lgamma", "multigammaln",
    "i0", "i0e", "i1", "i1e", "trapezoid", "diff", "angle", "conj", "real", "imag",
    "broadcast_shape", "renorm", "ldexp", "copysign", "nextafter",
    "take", "vander", "combinations", "bucketize",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift",
]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy().tolist()
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _binop(name, fn):
    def op(x, y, name=None):
        return op_call(name, fn, x, y)
    op.__name__ = name
    return op


def _unop(name, fn, nondiff=False):
    def op(x, name=None):
        return op_call(name, fn, x, nondiff=nondiff)
    op.__name__ = name
    return op


add = _binop("add", jnp.add)
subtract = _binop("subtract", jnp.subtract)
multiply = _binop("multiply", jnp.multiply)
divide = _binop("divide", jnp.true_divide)
floor_divide = _binop("floor_divide", jnp.floor_divide)
mod = _binop("mod", jnp.mod)
remainder = mod
atan2 = _binop("atan2", jnp.arctan2)
maximum = _binop("maximum", jnp.maximum)
minimum = _binop("minimum", jnp.minimum)
fmax = _binop("fmax", jnp.fmax)
fmin = _binop("fmin", jnp.fmin)
logaddexp = _binop("logaddexp", jnp.logaddexp)
hypot = _binop("hypot", jnp.hypot)
copysign = _binop("copysign", jnp.copysign)
nextafter = _binop("nextafter", jnp.nextafter)
heaviside = _binop("heaviside", jnp.heaviside)
gcd = _binop("gcd", jnp.gcd)
lcm = _binop("lcm", jnp.lcm)
bitwise_and = _binop("bitwise_and", lambda x, y: jnp.bitwise_and(x, y))
bitwise_or = _binop("bitwise_or", lambda x, y: jnp.bitwise_or(x, y))
bitwise_xor = _binop("bitwise_xor", lambda x, y: jnp.bitwise_xor(x, y))
bitwise_left_shift = _binop("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _binop("bitwise_right_shift", jnp.right_shift)
bitwise_not = _unop("bitwise_not", jnp.bitwise_not, nondiff=True)


def pow(x, y, name=None):
    return op_call("pow", jnp.power, x, y)


float_power = _binop("float_power", jnp.float_power)

sqrt = _unop("sqrt", jnp.sqrt)
rsqrt = _unop("rsqrt", jax.lax.rsqrt)
exp = _unop("exp", jnp.exp)
expm1 = _unop("expm1", jnp.expm1)
log = _unop("log", jnp.log)
log2 = _unop("log2", jnp.log2)
log10 = _unop("log10", jnp.log10)
log1p = _unop("log1p", jnp.log1p)
abs = _unop("abs", jnp.abs)
neg = _unop("neg", jnp.negative)
sign = _unop("sign", jnp.sign)
floor = _unop("floor", jnp.floor)
ceil = _unop("ceil", jnp.ceil)
round = _unop("round", jnp.round)
trunc = _unop("trunc", jnp.trunc)
frac = _unop("frac", lambda v: v - jnp.trunc(v))
sin = _unop("sin", jnp.sin)
cos = _unop("cos", jnp.cos)
tan = _unop("tan", jnp.tan)
asin = _unop("asin", jnp.arcsin)
acos = _unop("acos", jnp.arccos)
atan = _unop("atan", jnp.arctan)
sinh = _unop("sinh", jnp.sinh)
cosh = _unop("cosh", jnp.cosh)
tanh = _unop("tanh", jnp.tanh)
asinh = _unop("asinh", jnp.arcsinh)
acosh = _unop("acosh", jnp.arccosh)
atanh = _unop("atanh", jnp.arctanh)
deg2rad = _unop("deg2rad", jnp.deg2rad)
rad2deg = _unop("rad2deg", jnp.rad2deg)
reciprocal = _unop("reciprocal", jnp.reciprocal)
square = _unop("square", jnp.square)
erf = _unop("erf", jax.lax.erf)
erfinv = _unop("erfinv", jax.lax.erf_inv)
digamma = _unop("digamma", jax.lax.digamma)
lgamma = _unop("lgamma", jax.lax.lgamma)
i0 = _unop("i0", lambda v: jax.lax.bessel_i0e(v) * jnp.exp(jnp.abs(v)))
i0e = _unop("i0e", jax.lax.bessel_i0e)
i1 = _unop("i1", lambda v: jax.lax.bessel_i1e(v) * jnp.exp(jnp.abs(v)))
i1e = _unop("i1e", jax.lax.bessel_i1e)
isnan = _unop("isnan", jnp.isnan, nondiff=True)
isinf = _unop("isinf", jnp.isinf, nondiff=True)
isfinite = _unop("isfinite", jnp.isfinite, nondiff=True)
angle = _unop("angle", jnp.angle)
conj = _unop("conj", jnp.conj)
real = _unop("real", jnp.real)
imag = _unop("imag", jnp.imag)


def multigammaln(x, p, name=None):
    def impl(v):
        i = jnp.arange(p, dtype=v.dtype)
        return (p * (p - 1) / 4.0) * jnp.log(jnp.pi) + jnp.sum(
            jax.lax.lgamma(v[..., None] - i / 2.0), axis=-1)
    return op_call("multigammaln", impl, x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return op_call("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), x)


def softplus_math(x, beta=1.0, threshold=20.0, name=None):
    return op_call("softplus",
                   lambda v: jnp.where(v * beta > threshold, v,
                                       jnp.log1p(jnp.exp(beta * v)) / beta), x)


def logit(x, eps=None, name=None):
    def impl(v):
        vv = v if eps is None else jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(vv / (1.0 - vv))
    return op_call("logit", impl, x)


def clip(x, min=None, max=None, name=None):
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return op_call("clip", lambda v: jnp.clip(v, lo, hi), x)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return op_call("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)
    return op_call("lerp", lambda a, b: a + weight * (b - a), x, y)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return op_call("nan_to_num",
                   lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale._value if isinstance(scale, Tensor) else scale
    def impl(v):
        out = v * s + bias if bias_after_scale else (v + bias) * s
        return out
    return op_call("scale", impl, x)


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = globals()["scale"](x, scale, bias, bias_after_scale)
    return x._set_value(out._value)


def increment(x, value=1.0, name=None):
    return x._set_value(x._value + value)


def add_(x, y, name=None):
    return x._set_value(add(x, y)._value)


def subtract_(x, y, name=None):
    return x._set_value(subtract(x, y)._value)


def multiply_(x, y, name=None):
    return x._set_value(multiply(x, y)._value)


# -- reductions -------------------------------------------------------------
def _maybe_cast_reduce_dtype(v, dtype):
    d = dtype_mod.convert_dtype(dtype)
    return v.astype(d) if d is not None else v


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("sum", lambda v: jnp.sum(_maybe_cast_reduce_dtype(v, dtype),
                                            axis=ax, keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("mean", lambda v: jnp.mean(v, axis=ax, keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("nansum", lambda v: jnp.nansum(_maybe_cast_reduce_dtype(v, dtype),
                                                  axis=ax, keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("nanmean", lambda v: jnp.nanmean(v, axis=ax, keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("max", lambda v: jnp.max(v, axis=ax, keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("min", lambda v: jnp.min(v, axis=ax, keepdims=keepdim), x)


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _axis(axis)
    return op_call("prod", lambda v: jnp.prod(_maybe_cast_reduce_dtype(v, dtype),
                                              axis=ax, keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("logsumexp", lambda v: jax.scipy.special.logsumexp(v, axis=ax, keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _axis(axis)
    return op_call("count_nonzero",
                   lambda v: jnp.count_nonzero(v, axis=ax, keepdims=keepdim).astype(jnp.int64),
                   x, nondiff=True)


def cumsum(x, axis=None, dtype=None, name=None):
    def impl(v):
        vv = _maybe_cast_reduce_dtype(v, dtype)
        if axis is None:
            return jnp.cumsum(vv.reshape(-1))
        return jnp.cumsum(vv, axis=_axis(axis))
    return op_call("cumsum", impl, x)


def cumprod(x, dim=None, dtype=None, name=None):
    def impl(v):
        vv = _maybe_cast_reduce_dtype(v, dtype)
        if dim is None:
            return jnp.cumprod(vv.reshape(-1))
        return jnp.cumprod(vv, axis=_axis(dim))
    return op_call("cumprod", impl, x)


def cummax(x, axis=None, dtype="int64", name=None):
    ax = -1 if axis is None else _axis(axis)
    v = x._value.reshape(-1) if axis is None else x._value
    vals = op_call("cummax", lambda t: jax.lax.cummax(t, axis=ax if ax >= 0 else t.ndim + ax),
                   Tensor(v, stop_gradient=x.stop_gradient) if axis is None else x)
    # index of running max: positions where value equals running max, take last
    n = v.shape[ax]
    pos = jnp.arange(n).reshape([-1 if i == (ax % v.ndim) else 1 for i in range(v.ndim)])
    eq = (v == vals._value)
    ind = jax.lax.cummax(jnp.where(eq, pos, -1), axis=ax % v.ndim)
    return vals, Tensor(ind.astype(dtype_mod.convert_dtype(dtype)))


def cummin(x, axis=None, dtype="int64", name=None):
    ax = -1 if axis is None else _axis(axis)
    v = x._value.reshape(-1) if axis is None else x._value
    vals = op_call("cummin", lambda t: jax.lax.cummin(t, axis=ax if ax >= 0 else t.ndim + ax),
                   Tensor(v, stop_gradient=x.stop_gradient) if axis is None else x)
    n = v.shape[ax]
    pos = jnp.arange(n).reshape([-1 if i == (ax % v.ndim) else 1 for i in range(v.ndim)])
    eq = (v == vals._value)
    ind = jax.lax.cummax(jnp.where(eq, pos, -1), axis=ax % v.ndim)
    return vals, Tensor(ind.astype(dtype_mod.convert_dtype(dtype)))


# -- linear-algebra-lite (kept here to mirror paddle.tensor.math) -----------
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return op_call("addmm", lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def outer(x, y, name=None):
    return op_call("outer", lambda a, b: jnp.outer(a, b), x, y)


def inner(x, y, name=None):
    return op_call("inner", lambda a, b: jnp.inner(a, b), x, y)


def dot(x, y, name=None):
    def impl(a, b):
        if a.ndim == 1:
            return jnp.sum(a * b)
        return jnp.sum(a * b, axis=-1)
    return op_call("dot", impl, x, y)


def cross(x, y, axis=None, name=None):
    ax = 9 if axis is None else _axis(axis)  # paddle default: first dim of size 3
    def impl(a, b):
        axis_ = ax
        if axis_ == 9:
            for i, s in enumerate(a.shape):
                if s == 3:
                    axis_ = i
                    break
        return jnp.cross(a, b, axis=axis_)
    return op_call("cross", impl, x, y)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return op_call("trapezoid", lambda yy, xx: jax.scipy.integrate.trapezoid(yy, xx, axis=axis), y, x)
    d = 1.0 if dx is None else dx
    return op_call("trapezoid", lambda yy: jax.scipy.integrate.trapezoid(yy, dx=d, axis=axis), y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._value if isinstance(prepend, Tensor) else prepend
    app = append._value if isinstance(append, Tensor) else append
    return op_call("diff", lambda v: jnp.diff(v, n=n, axis=axis, prepend=pre, append=app), x)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def renorm(x, p, axis, max_norm, name=None):
    def impl(v):
        dims = [i for i in range(v.ndim) if i != axis % v.ndim]
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor
    return op_call("renorm", impl, x)


def ldexp(x, y, name=None):
    return op_call("ldexp", lambda a, b: a * (2.0 ** b.astype(jnp.float32)), x, y)


def take(x, index, mode="raise", name=None):
    def impl(v, idx):
        flat = v.reshape(-1)
        n = flat.shape[0]
        ii = idx.astype(jnp.int32)
        if mode == "wrap":
            ii = jnp.mod(ii, n)
        elif mode == "clip":
            ii = jnp.clip(ii, -n, n - 1)
        ii = jnp.where(ii < 0, ii + n, ii)
        return flat[ii]
    return op_call("take", impl, x, index)


def vander(x, n=None, increasing=False, name=None):
    return op_call("vander", lambda v: jnp.vander(v, N=n, increasing=increasing), x)


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    v = x._value
    n = v.shape[0]
    it = itertools.combinations_with_replacement(range(n), r) if with_replacement \
        else itertools.combinations(range(n), r)
    idx = np.array(list(it), dtype=np.int32)
    if idx.size == 0:
        return Tensor(jnp.zeros((0, r), v.dtype))
    return op_call("combinations", lambda vv: vv[jnp.asarray(idx)], x)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else jnp.int64
    return op_call("bucketize",
                   lambda v, s: jnp.searchsorted(s, v, side=side).astype(d),
                   x, sorted_sequence, nondiff=True)


def add_n(inputs, name=None):
    """Sum of a list of same-shape tensors (reference math.py add_n)."""
    if isinstance(inputs, Tensor):
        return op_call("add_n", lambda v: v, inputs)
    ts = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
          for t in inputs]

    def impl(*vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out
    return op_call("add_n", impl, *ts)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    """reference math.py logcumsumexp — numerically stable cumulative
    logsumexp via the running-max recurrence (an associative scan on the
    (max, sumexp) pair, so XLA parallelizes it)."""
    def impl(v):
        vv = v.reshape(-1) if axis is None else v
        ax = 0 if axis is None else axis

        def combine(a, b):
            am, asum = a
            bm, bsum = b
            m = jnp.maximum(am, bm)
            # exp(-inf - -inf) is nan: when a side's max equals the joint
            # max (incl. the all--inf case) its scale is exactly 1
            ea = jnp.where(am == m, 1.0, jnp.exp(am - m))
            eb = jnp.where(bm == m, 1.0, jnp.exp(bm - m))
            return m, asum * ea + bsum * eb
        m, s = jax.lax.associative_scan(
            combine, (vv, jnp.ones_like(vv)), axis=ax)
        out = m + jnp.log(s)
        return out.astype(dtype) if dtype is not None else out
    return op_call("logcumsumexp", impl, x)


def sinc(x, name=None):
    """reference math.py sinc (normalized: sin(pi x)/(pi x), 1 at 0)."""
    return op_call("sinc", jnp.sinc, x)


def frexp(x, name=None):
    """reference math.py frexp -> (mantissa, exponent)."""
    def impl(v):
        m, e = jnp.frexp(v)
        return m, e.astype(jnp.int32)
    return op_call("frexp", impl, x, nondiff=True)


def gammaln(x, name=None):
    """alias of lgamma (reference math.py gammaln)."""
    return lgamma(x)


def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (reference gammainc)."""
    from jax.scipy import special as jsp
    return op_call("gammainc", jsp.gammainc, x, y)


def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y)."""
    from jax.scipy import special as jsp
    return op_call("gammaincc", jsp.gammaincc, x, y)


def polygamma(x, n, name=None):
    """reference math.py polygamma(x, n) — n-th derivative of digamma."""
    from jax.scipy import special as jsp
    return op_call("polygamma", lambda v: jsp.polygamma(n, v), x)


def floor_mod(x, y, name=None):
    """alias of mod (reference math.py floor_mod)."""
    return mod(x, y)


def sgn(x, name=None):
    """reference math.py sgn: sign for real, unit phasor for complex."""
    def impl(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.where(mag == 0, 1, mag))
        return jnp.sign(v)
    return op_call("sgn", impl, x, nondiff=True)


def negative(x, name=None):
    """alias of neg."""
    return neg(x)


def positive(x, name=None):
    """reference math.py positive (identity on numeric tensors)."""
    return op_call("positive", lambda v: +v, x)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """reference math.py cumulative_trapezoid."""
    def impl(yv, *rest):
        import jax.numpy as jnp
        d = dx if dx is not None else 1.0
        y1 = jnp.take(yv, jnp.arange(1, yv.shape[axis]), axis=axis)
        y0 = jnp.take(yv, jnp.arange(0, yv.shape[axis] - 1), axis=axis)
        if rest:
            xv = rest[0]
            x1 = jnp.take(xv, jnp.arange(1, xv.shape[axis]), axis=axis)
            x0 = jnp.take(xv, jnp.arange(0, xv.shape[axis] - 1), axis=axis)
            d = x1 - x0
        return jnp.cumsum((y1 + y0) * d / 2.0, axis=axis)
    args = (y,) if x is None else (y, x)
    return op_call("cumulative_trapezoid", impl, *args)


__all__ += ["add_n", "logcumsumexp", "sinc", "frexp", "gammaln", "gammainc",
            "gammaincc", "polygamma", "floor_mod", "sgn", "negative",
            "positive", "cumulative_trapezoid"]
