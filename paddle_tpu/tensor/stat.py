"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import op_call

__all__ = ["std", "var"]


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return op_call("var", lambda v: jnp.var(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                            keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return op_call("std", lambda v: jnp.std(v, axis=_axis(axis), ddof=1 if unbiased else 0,
                                            keepdims=keepdim), x)
