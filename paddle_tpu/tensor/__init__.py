"""Functional tensor ops + Tensor method patching.

Mirrors the reference's monkey-patching of math methods onto Tensor
(python/paddle/tensor/__init__.py + pybind eager_method.cc): every public
functional op whose first parameter is a tensor is attached as a Tensor
method, and the arithmetic dunders route through dispatch so they record on
the autograd tape.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, Parameter, to_tensor, is_tensor
from ..core.dispatch import op_call

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403

from . import creation, math, manipulation, linalg, logic, search, stat, random


# ---------------------------------------------------------------------------
# Indexing
# ---------------------------------------------------------------------------
def _process_index(idx):
    """Convert Tensor components of an index to jax arrays."""
    def conv(i):
        if isinstance(i, Tensor):
            v = i._value
            if jnp.issubdtype(v.dtype, jnp.integer):
                return v.astype(jnp.int32)
            return v
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i
    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def _getitem(self, idx):
    pidx = _process_index(idx)
    return op_call("getitem", lambda v: v[pidx], self)


def _setitem(self, idx, value):
    pidx = _process_index(idx)
    if isinstance(value, Tensor):
        out = op_call("setitem", lambda v, val: v.at[pidx].set(val.astype(v.dtype)
                                                               if val.dtype != v.dtype else val),
                      self, value)
    else:
        out = op_call("setitem", lambda v: v.at[pidx].set(jnp.asarray(value, v.dtype)), self)
    # rebind: the tensor now aliases the updated value and its grad node
    self._value = out._value
    self._grad_node = out._grad_node
    self._out_index = out._out_index
    self.stop_gradient = out.stop_gradient


Tensor.__getitem__ = _getitem
Tensor.__setitem__ = _setitem


# ---------------------------------------------------------------------------
# Arithmetic dunders
# ---------------------------------------------------------------------------
def _coerce(other):
    if isinstance(other, Tensor):
        return other
    return other  # raw scalars/arrays pass straight into jnp


def _binary(name, fn, reflexive=False):
    def method(self, other):
        other = _coerce(other)
        if reflexive:
            return op_call(name, lambda a, b: fn(b, a), self, other) \
                if isinstance(other, Tensor) else op_call(name, lambda a: fn(other, a), self)
        return op_call(name, fn, self, other)
    return method


Tensor.__add__ = _binary("add", jnp.add)
Tensor.__radd__ = _binary("add", jnp.add)
Tensor.__sub__ = _binary("subtract", jnp.subtract)
Tensor.__rsub__ = _binary("subtract", jnp.subtract, reflexive=True)
Tensor.__mul__ = _binary("multiply", jnp.multiply)
Tensor.__rmul__ = _binary("multiply", jnp.multiply)
Tensor.__truediv__ = _binary("divide", jnp.true_divide)
Tensor.__rtruediv__ = _binary("divide", jnp.true_divide, reflexive=True)
Tensor.__floordiv__ = _binary("floor_divide", jnp.floor_divide)
Tensor.__rfloordiv__ = _binary("floor_divide", jnp.floor_divide, reflexive=True)
Tensor.__mod__ = _binary("mod", jnp.mod)
Tensor.__rmod__ = _binary("mod", jnp.mod, reflexive=True)
Tensor.__pow__ = _binary("pow", jnp.power)
Tensor.__rpow__ = _binary("pow", jnp.power, reflexive=True)
Tensor.__matmul__ = _binary("matmul", jnp.matmul)
Tensor.__rmatmul__ = _binary("matmul", jnp.matmul, reflexive=True)


def _neg(self):
    return op_call("neg", jnp.negative, self)


def _abs(self):
    return op_call("abs", jnp.abs, self)


Tensor.__neg__ = _neg
Tensor.__abs__ = _abs


def _cmp_method(name, fn):
    def method(self, other):
        return op_call(name, fn, self, other, nondiff=True)
    return method


Tensor.__eq__ = _cmp_method("equal", jnp.equal)
Tensor.__ne__ = _cmp_method("not_equal", jnp.not_equal)
Tensor.__lt__ = _cmp_method("less_than", jnp.less)
Tensor.__le__ = _cmp_method("less_equal", jnp.less_equal)
Tensor.__gt__ = _cmp_method("greater_than", jnp.greater)
Tensor.__ge__ = _cmp_method("greater_equal", jnp.greater_equal)

Tensor.__invert__ = lambda self: op_call("invert", lambda v: ~v, self, nondiff=True)
Tensor.__and__ = _cmp_method("and", lambda a, b: a & b)
Tensor.__or__ = _cmp_method("or", lambda a, b: a | b)
Tensor.__xor__ = _cmp_method("xor", lambda a, b: a ^ b)
Tensor.__lshift__ = _cmp_method("lshift", jnp.left_shift)
Tensor.__rshift__ = _cmp_method("rshift", jnp.right_shift)

# re-register hash (defining __eq__ via class attr assignment clears it on
# some python versions only at class creation; ensure identity hash stays)
Tensor.__hash__ = lambda self: id(self)


# ---------------------------------------------------------------------------
# Attach functional ops as methods
# ---------------------------------------------------------------------------
_METHOD_SOURCES = [creation, math, manipulation, linalg, logic, search, stat, random]
_SKIP = {"to_tensor", "meshgrid", "broadcast_tensors", "multi_dot", "einsum",
         "concat", "stack", "assign", "zeros", "ones", "full", "arange",
         "linspace", "logspace", "eye", "rand", "randn", "randint", "randperm",
         "uniform", "normal", "create_parameter", "tril_indices", "triu_indices",
         "broadcast_shape", "scatter_nd", "histogram_bin_edges", "combinations",
         "empty", "log_normal", "standard_normal"}

for _mod in _METHOD_SOURCES:
    for _name in getattr(_mod, "__all__", []):
        if _name in _SKIP:
            continue
        _fn = getattr(_mod, _name)
        if callable(_fn) and not hasattr(Tensor, _name):
            setattr(Tensor, _name, _fn)

# paddle-style aliases
Tensor.add = math.add
Tensor.add_ = math.add_
Tensor.multiply = math.multiply
Tensor.pow = math.pow
Tensor.abs = math.abs
Tensor.sum = math.sum
Tensor.mean = math.mean
Tensor.max = math.max
Tensor.min = math.min
Tensor.matmul = linalg.matmul
Tensor.mm = linalg.mm
Tensor.norm = linalg.norm
Tensor.reshape = manipulation.reshape
Tensor.transpose = manipulation.transpose
Tensor.flatten = manipulation.flatten
Tensor.split = manipulation.split
Tensor.chunk = manipulation.chunk
Tensor.squeeze = manipulation.squeeze
Tensor.unsqueeze = manipulation.unsqueeze
Tensor.expand = manipulation.expand
Tensor.tile = manipulation.tile
Tensor.gather = manipulation.gather
Tensor.scatter = manipulation.scatter
Tensor.topk = search.topk
Tensor.argmax = search.argmax
Tensor.argmin = search.argmin
Tensor.argsort = search.argsort
Tensor.sort = search.sort
Tensor.unbind = manipulation.unbind
Tensor.T = property(lambda self: op_call("T", lambda v: v.T, self))
Tensor.mT = property(lambda self: op_call("mT", lambda v: jnp.swapaxes(v, -1, -2), self))


# ---------------------------------------------------------------------------
# Mechanical in-place variants (reference tensor/__init__.py's *_ surface):
# every listed op gains `<name>_` = "compute out-of-place, write back into
# the tensor's storage" — the reference's in-place kernels collapse onto
# _set_value since jax arrays are immutable.
# ---------------------------------------------------------------------------
import sys as _sys

_INPLACE_BASES = [
    # unary math
    "abs", "acos", "acosh", "asin", "asinh", "atan", "atanh", "ceil", "cos",
    "cosh", "digamma", "erfinv", "exp", "expm1", "floor", "frac", "i0",
    "lgamma", "gammaln", "log", "log10", "log1p", "log2", "logit", "neg",
    "reciprocal", "round", "rsqrt", "sin", "sinh", "sqrt", "square", "tan",
    "tanh", "trunc", "nan_to_num", "sgn",
    # binary / misc
    "pow", "divide", "floor_divide", "mod", "remainder", "copysign",
    "hypot", "lerp", "ldexp", "gcd", "lcm", "gammainc", "gammaincc",
    "polygamma", "renorm", "index_add", "index_fill", "index_put",
    "masked_fill", "masked_scatter", "put_along_axis", "clip",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift",
    "logical_and", "logical_or", "logical_xor", "logical_not",
    "equal", "not_equal", "greater_equal", "greater_than", "less_equal",
    "less_than",
]

_this = _sys.modules[__name__]


def _make_inplace(base_name, base_fn):
    def _inplace(x, *args, **kwargs):
        # the write-back goes through _set_value, which detaches from the
        # tape — applied to a grad-requiring tensor this would SILENTLY
        # corrupt autograd: a non-leaf drops gradients to its upstream
        # producers, a leaf mutates the value its pending grads refer to.
        # The reference raises for both under grad mode ("Leaf Var that
        # doesn't stop gradient can't use inplace strategy" / the
        # inplace-version check); match it (ADVICE r5 #2).
        from ..core.dispatch import is_grad_enabled
        if is_grad_enabled() and not x.stop_gradient:
            kind = "leaf" if x.is_leaf else "non-leaf"
            raise RuntimeError(
                f"{base_name}_: in-place operation on a {kind} tensor that "
                "requires grad is not supported — the write-back would "
                "detach it from the autograd tape (reference in-place "
                f"guard). Use the out-of-place `{base_name}`, or wrap the "
                "call in paddle.no_grad().")
        out = base_fn(x.detach(), *args, **kwargs)
        if out._value.shape != x._value.shape:
            raise ValueError(
                f"{base_name}_: in-place result shape "
                f"{out._value.shape} != tensor shape {x._value.shape} — "
                "in-place ops must preserve shape (use the out-of-place "
                f"{base_name} instead)")
        return x._set_value(out._value)
    _inplace.__name__ = base_name + "_"
    _inplace.__qualname__ = base_name + "_"
    _inplace.__doc__ = (f"In-place variant of `{base_name}` (reference "
                        f"tensor API {base_name}_): writes the result back "
                        "into this tensor's storage.")
    return _inplace


for _base in _INPLACE_BASES:
    _iname = _base + "_"
    if hasattr(_this, _iname):
        continue
    _fn = getattr(_this, _base, None)
    if _fn is None:
        continue
    _ip = _make_inplace(_base, _fn)
    setattr(_this, _iname, _ip)
    if not hasattr(Tensor, _iname):
        setattr(Tensor, _iname, _ip)

# aliases the reference exposes at tensor level
bitwise_invert = bitwise_not          # reference math.py bitwise_invert
bitwise_invert_ = bitwise_not_
