"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py).

On TPU these are mostly free: XLA fuses reshapes/transposes into consumers;
gather/scatter lower to efficient dynamic-slice HLO.
"""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call
from ..core import dtype as dtype_mod

__all__ = [
    "reshape", "reshape_", "flatten", "squeeze", "squeeze_", "unsqueeze",
    "unsqueeze_", "concat", "stack", "split", "tensor_split", "vsplit", "hsplit",
    "dsplit", "chunk", "tile", "expand", "expand_as", "broadcast_to",
    "broadcast_tensors", "gather", "gather_nd", "scatter", "scatter_",
    "scatter_nd", "scatter_nd_add", "index_select", "index_add", "index_put",
    "masked_select", "masked_fill", "masked_scatter", "where", "roll", "flip",
    "rot90", "repeat_interleave", "take_along_axis", "put_along_axis", "unbind",
    "unstack", "strided_slice", "slice", "crop", "pad", "transpose", "transpose_",
    "moveaxis", "swapaxes", "swapdims", "t", "as_strided", "view", "view_as",
    "unfold", "cast", "cast_", "unique", "unique_consecutive", "flip_",
    "fill_diagonal_", "diagonal", "kron", "rank", "shard_index",
    "tolist", "atleast_1d", "atleast_2d", "atleast_3d", "select_scatter",
    "index_fill", "tensordot", "as_complex", "as_real", "numel",
]


def _axes(axis):
    if isinstance(axis, Tensor):
        a = axis.numpy().tolist()
        return a if isinstance(a, list) else int(a)
    return axis


def reshape(x, shape, name=None):
    shape = tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape) \
        if not isinstance(shape, Tensor) else tuple(shape.numpy().tolist())
    # paddle semantics: 0 means copy dim from input
    xs = x.shape if isinstance(x, Tensor) else list(np.shape(x))
    shape = tuple(xs[i] if s == 0 and i < len(xs) else s for i, s in enumerate(shape))
    return op_call("reshape", lambda v: jnp.reshape(v, shape), x)


def reshape_(x, shape, name=None):
    return x._set_value(reshape(x.detach(), shape)._value)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    d = dtype_mod.convert_dtype(shape_or_dtype)
    return op_call("view_dtype", lambda v: v.view(d), x, nondiff=True)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def impl(v):
        nd = v.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = v.shape[:s] + (-1,) + v.shape[e + 1:]
        return jnp.reshape(v, new_shape)
    return op_call("flatten", impl, x)


def squeeze(x, axis=None, name=None):
    ax = _axes(axis)
    def impl(v):
        if ax is None:
            return jnp.squeeze(v)
        axes = ax if isinstance(ax, (list, tuple)) else [ax]
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v
    return op_call("squeeze", impl, x)


def squeeze_(x, axis=None, name=None):
    return x._set_value(squeeze(x.detach(), axis)._value)


def unsqueeze(x, axis, name=None):
    ax = _axes(axis)
    axes = ax if isinstance(ax, (list, tuple)) else [ax]
    return op_call("unsqueeze", lambda v: jnp.expand_dims(v, tuple(axes)), x)


def unsqueeze_(x, axis, name=None):
    return x._set_value(unsqueeze(x.detach(), axis)._value)


def concat(x, axis=0, name=None):
    tensors = list(x)
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return op_call("concat", lambda *vs: jnp.concatenate(vs, axis=ax), *tensors)


def stack(x, axis=0, name=None):
    tensors = list(x)
    return op_call("stack", lambda *vs: jnp.stack(vs, axis=axis), *tensors)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    n = x.shape[ax]
    if isinstance(num_or_sections, int):
        if n % num_or_sections != 0:
            raise ValueError(
                f"paddle.split: axis {ax} length {n} is not divisible by "
                f"num_or_sections={num_or_sections} (use tensor_split for "
                f"uneven splits)")
        sizes = [n // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = n - sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1])
    outs = []
    for off, sz in zip(offsets, sizes):
        outs.append(op_call("slice", lambda v, o=off, s=sz: jax.lax.slice_in_dim(v, o, o + s, axis=ax), x))
    return outs


def tensor_split(x, num_or_indices, axis=0, name=None):
    ax = int(axis)
    n = x.shape[ax]
    if isinstance(num_or_indices, int):
        k, m = divmod(n, num_or_indices)
        sizes = [k + 1] * m + [k] * (num_or_indices - m)
    else:
        idx = [0] + [int(i) for i in num_or_indices] + [n]
        sizes = [idx[i + 1] - idx[i] for i in range(len(idx) - 1)]
    return split(x, sizes, axis=ax)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return tensor_split(x, chunks, axis=axis)


def tile(x, repeat_times, name=None):
    reps = tuple(int(r._value) if isinstance(r, Tensor) else int(r) for r in repeat_times) \
        if not isinstance(repeat_times, Tensor) else tuple(repeat_times.numpy().tolist())
    return op_call("tile", lambda v: jnp.tile(v, reps), x)


def expand(x, shape, name=None):
    shp = tuple(int(s._value) if isinstance(s, Tensor) else int(s) for s in shape) \
        if not isinstance(shape, Tensor) else tuple(shape.numpy().tolist())
    def impl(v):
        tgt = list(shp)
        # -1 means keep input dim
        off = len(tgt) - v.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - off]
        return jnp.broadcast_to(v, tuple(tgt))
    return op_call("expand", impl, x)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    inputs = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
              for t in inputs]
    shape = np.broadcast_shapes(*[t._value.shape for t in inputs])
    return [op_call("broadcast_to", lambda v, s=shape: jnp.broadcast_to(v, s), t)
            for t in inputs]


def cast(x, dtype):
    d = dtype_mod.convert_dtype(dtype)
    return op_call("cast", lambda v: v.astype(d), x)


def cast_(x, dtype):
    return x._set_value(cast(x.detach(), dtype)._value)


def gather(x, index, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return op_call("gather", lambda v, i: jnp.take(v, i.astype(jnp.int32), axis=ax), x, index)


def gather_nd(x, index, name=None):
    def impl(v, idx):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        comps = tuple(idx[..., i] for i in range(k))
        return v[comps]
    return op_call("gather_nd", impl, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def impl(v, idx, upd):
        idx = idx.astype(jnp.int32).reshape(-1)
        if overwrite:
            return v.at[idx].set(upd)
        # paddle overwrite=False: zero target rows then accumulate
        zeroed = v.at[idx].set(jnp.zeros_like(upd))
        return zeroed.at[idx].add(upd)
    return op_call("scatter", impl, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    return x._set_value(scatter(x.detach(), index, updates, overwrite)._value)


def scatter_nd(index, updates, shape, name=None):
    shp = tuple(int(s) for s in shape)
    def impl(idx, upd):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        base = jnp.zeros(shp, upd.dtype)
        comps = tuple(idx[..., i] for i in range(k))
        return base.at[comps].add(upd)
    return op_call("scatter_nd", impl, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def impl(v, idx, upd):
        idx = idx.astype(jnp.int32)
        k = idx.shape[-1]
        comps = tuple(idx[..., i] for i in range(k))
        return v.at[comps].add(upd)
    return op_call("scatter_nd_add", impl, x, index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_add(x, index, axis, value, name=None):
    def impl(v, idx, val):
        idx = idx.astype(jnp.int32)
        moved = jnp.moveaxis(v, axis, 0)
        val_m = jnp.moveaxis(val, axis, 0)
        out = moved.at[idx].add(val_m)
        return jnp.moveaxis(out, 0, axis)
    return op_call("index_add", impl, x, index, value)


def index_fill(x, index, axis, value, name=None):
    def impl(v, idx):
        idx = idx.astype(jnp.int32)
        moved = jnp.moveaxis(v, axis, 0)
        fill = jnp.asarray(value, v.dtype)
        out = moved.at[idx].set(jnp.broadcast_to(fill, (idx.shape[0],) + moved.shape[1:]))
        return jnp.moveaxis(out, 0, axis)
    return op_call("index_fill", impl, x, index)


def index_put(x, indices, value, accumulate=False, name=None):
    idx_vals = tuple(i._value.astype(jnp.int32) if isinstance(i, Tensor) else i for i in indices)
    def impl(v, val):
        if accumulate:
            return v.at[idx_vals].add(val)
        return v.at[idx_vals].set(val)
    return op_call("index_put", impl, x, value)


def masked_select(x, mask, name=None):
    # dynamic output shape: computed eagerly via numpy (not jittable — same
    # caveat as reference dygraph-only ops)
    v = np.asarray(x._value)
    m = np.asarray(mask._value)
    return Tensor(jnp.asarray(v[m]))


def masked_fill(x, mask, value, name=None):
    val = value._value if isinstance(value, Tensor) else value
    return op_call("masked_fill", lambda v, m: jnp.where(m, jnp.asarray(val, v.dtype), v), x, mask)


def masked_scatter(x, mask, value, name=None):
    def impl(v, m, val):
        flat_val = val.reshape(-1)
        mi = jnp.cumsum(m.reshape(-1).astype(jnp.int32)) - 1
        picked = flat_val[jnp.clip(mi, 0, flat_val.shape[0] - 1)].reshape(v.shape)
        return jnp.where(m, picked, v)
    return op_call("masked_scatter", impl, x, mask, value)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return op_call("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def nonzero(x, as_tuple=False):
    v = np.asarray(x._value)
    nz = np.nonzero(v)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i, dtype=jnp.int64)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1), dtype=jnp.int64))


def roll(x, shifts, axis=None, name=None):
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else int(shifts)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return op_call("roll", lambda v: jnp.roll(v, sh, axis=ax), x)


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return op_call("flip", lambda v: jnp.flip(v, axis=ax), x)


def flip_(x, axis, name=None):
    return x._set_value(flip(x.detach(), axis)._value)


def rot90(x, k=1, axes=(0, 1), name=None):
    return op_call("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = np.asarray(repeats._value)
        v = np.asarray(x._value)
        return Tensor(jnp.asarray(np.repeat(v, reps, axis=axis)))
    return op_call("repeat_interleave", lambda v: jnp.repeat(v, repeats, axis=axis), x)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return op_call("take_along_axis",
                   lambda v, i: jnp.take_along_axis(v, i.astype(jnp.int32), axis=axis),
                   arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    def impl(v, idx, val):
        idx = idx.astype(jnp.int32)
        val = jnp.broadcast_to(jnp.asarray(val, v.dtype), idx.shape)
        if reduce == "assign":
            return jnp.put_along_axis(v, idx, val, axis=axis, inplace=False)
        mode = {"add": "add", "multiply": "multiply", "mul": "multiply",
                "amin": "min", "amax": "max"}[reduce]
        moved = jnp.moveaxis(v, axis, -1)
        midx = jnp.moveaxis(idx, axis, -1)
        mval = jnp.moveaxis(val, axis, -1)
        upd = getattr(moved.at[..., 0], "set")  # placeholder; use scatter via at
        # scatter along last axis with batch dims
        def scat(row, irow, vrow):
            if mode == "add":
                return row.at[irow].add(vrow)
            if mode == "multiply":
                return row.at[irow].multiply(vrow)
            if mode == "min":
                return row.at[irow].min(vrow)
            return row.at[irow].max(vrow)
        flat_m = moved.reshape(-1, moved.shape[-1])
        flat_i = midx.reshape(-1, midx.shape[-1])
        flat_v = mval.reshape(-1, mval.shape[-1])
        out = jax.vmap(scat)(flat_m, flat_i, flat_v)
        return jnp.moveaxis(out.reshape(moved.shape), -1, axis)
    if isinstance(values, (int, float)):
        values = Tensor(jnp.asarray(values))
    return op_call("put_along_axis", impl, arr, indices, values)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    outs = op_call("unbind", lambda v: tuple(jnp.squeeze(s, axis=axis) for s in
                                             jnp.split(v, n, axis=axis)), x)
    return list(outs)


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def slice(x, axes, starts, ends, name=None):
    def impl(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            s = int(s._value) if isinstance(s, Tensor) else int(s)
            e = int(e._value) if isinstance(e, Tensor) else int(e)
            idx[a] = builtins_slice(s, e)
        return v[tuple(idx)]
    return op_call("slice", impl, x)


builtins_slice = builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    def impl(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins_slice(int(s), int(e), int(st))
        return v[tuple(idx)]
    return op_call("strided_slice", impl, x)


def crop(x, shape=None, offsets=None, name=None):
    shp = [int(s) for s in (shape or x.shape)]
    offs = [int(o) for o in (offsets or [0] * x.ndim)]
    shp = [x.shape[i] if s == -1 else s for i, s in enumerate(shp)]
    def impl(v):
        return jax.lax.dynamic_slice(v, offs, shp)
    return op_call("crop", impl, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad semantics: `pad` is per-axis pairs, innermost
    last when len(pad) < 2*ndim (torch convention used by paddle)."""
    if isinstance(pad, Tensor):
        pad = pad.numpy().tolist()
    pad = [int(p) for p in pad]
    def impl(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # innermost-dims-last convention
            k = len(pad) // 2
            widths = [(0, 0)] * (nd - k) + [
                (pad[2 * (k - 1 - i)], pad[2 * (k - 1 - i) + 1]) for i in range(k)]
            if data_format in ("NHWC", "NLC", "NDHWC") and k < nd - 1:
                # channel-last: pad spatial dims (all but first and last)
                widths = [(0, 0)] + widths[2:] + [(0, 0)] if len(widths) == nd else widths
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, widths, mode=jmode, constant_values=value)
        return jnp.pad(v, widths, mode=jmode)
    return op_call("pad", impl, x)


def transpose(x, perm, name=None):
    perm = tuple(int(p) for p in perm)
    return op_call("transpose", lambda v: jnp.transpose(v, perm), x)


def transpose_(x, perm, name=None):
    return x._set_value(transpose(x.detach(), perm)._value)


def moveaxis(x, source, destination, name=None):
    return op_call("moveaxis", lambda v: jnp.moveaxis(v, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return op_call("swapaxes", lambda v: jnp.swapaxes(v, axis0, axis1), x)


swapdims = swapaxes


def t(x, name=None):
    def impl(v):
        if v.ndim < 2:
            return v
        return v.T
    return op_call("t", impl, x)


def as_strided(x, shape, stride, offset=0, name=None):
    def impl(v):
        flat = v.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for d, (s, st) in enumerate(zip(shape, stride)):
            r = np.arange(s) * st
            idx = idx + r.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[jnp.asarray(idx)]
    return op_call("as_strided", impl, x)


def unfold(x, axis, size, step, name=None):
    def impl(v):
        n = v.shape[axis]
        num = (n - size) // step + 1
        starts = np.arange(num) * step
        slices = [jax.lax.slice_in_dim(v, int(s), int(s) + size, axis=axis) for s in starts]
        return jnp.stack(slices, axis=axis if axis >= 0 else v.ndim + axis)
    return op_call("unfold", impl, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    v = np.asarray(x._value)
    res = np.unique(v, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    v = np.asarray(x._value)
    if axis is None:
        v = v.reshape(-1)
        keep = np.concatenate([[True], v[1:] != v[:-1]])
        out = v[keep]
        inv = np.cumsum(keep) - 1
        counts = np.diff(np.concatenate([np.nonzero(keep)[0], [len(v)]]))
    else:
        raise NotImplementedError("unique_consecutive with axis")
    res = [Tensor(jnp.asarray(out))]
    if return_inverse:
        res.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        res.append(Tensor(jnp.asarray(counts)))
    return res[0] if len(res) == 1 else tuple(res)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    v = x._value
    n = min(v.shape[-2:]) - builtins.abs(offset)
    i = jnp.arange(n)
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    x._set_value(v.at[..., r, c].set(value))
    return x


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return op_call("diagonal", lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), x)


def kron(x, y, name=None):
    return op_call("kron", lambda a, b: jnp.kron(a, b), x, y)


def rank(x):
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=jnp.int64))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    def impl(v):
        in_shard = (v // shard_size) == shard_id
        return jnp.where(in_shard, v % shard_size, ignore_value)
    return op_call("shard_index", impl, input, nondiff=True)


def tolist(x):
    return x.numpy().tolist()


def atleast_1d(*inputs, name=None):
    outs = [op_call("atleast_1d", jnp.atleast_1d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [op_call("atleast_2d", jnp.atleast_2d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [op_call("atleast_3d", jnp.atleast_3d, t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def select_scatter(x, values, axis, index, name=None):
    def impl(v, val):
        idx = [builtins_slice(None)] * v.ndim
        idx[axis] = index
        return v.at[tuple(idx)].set(val)
    return op_call("select_scatter", impl, x, values)


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.numpy().tolist()
    return op_call("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def as_complex(x, name=None):
    return op_call("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


def as_real(x, name=None):
    return op_call("as_real", lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def unflatten(x, axis, shape, name=None):
    """Split one axis into the given shape (reference unflatten)."""
    def impl(v):
        ax = axis % v.ndim
        tgt = list(shape)
        if -1 in tgt:
            known = int(np.prod([s for s in tgt if s != -1]))
            tgt[tgt.index(-1)] = v.shape[ax] // known
        return v.reshape(v.shape[:ax] + tuple(tgt) + v.shape[ax + 1:])
    return op_call("unflatten", impl, x)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write y onto the selected diagonal of x (reference
    diagonal_scatter)."""
    def impl(v, w):
        a1, a2 = axis1 % v.ndim, axis2 % v.ndim
        n1, n2 = v.shape[a1], v.shape[a2]
        if offset >= 0:
            i = jnp.arange(min(n1, n2 - offset))
            j = i + offset
        else:
            j = jnp.arange(min(n2, n1 + offset))
            i = j - offset
        # move the two diagonal axes to the front for a clean scatter;
        # axes are normalized first — argsort of a perm with negatives is
        # NOT its inverse
        perm = [a1, a2] + [a for a in range(v.ndim) if a not in (a1, a2)]
        inv = np.argsort(perm)
        vt = jnp.transpose(v, perm)
        wt = jnp.moveaxis(w, -1, 0) if w.ndim == v.ndim - 1 else w
        vt = vt.at[i, j].set(wt)
        return jnp.transpose(vt, inv)
    return op_call("diagonal_scatter", impl, x, y)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Write `value` into the strided slice of x (reference
    slice_scatter)."""
    def impl(v, w):
        idx = [builtins.slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins.slice(s, e, st)
        return v.at[tuple(idx)].set(w)
    return op_call("slice_scatter", impl, x, value)


def reverse(x, axis, name=None):
    """alias of flip (reference manipulation reverse)."""
    return flip(x, axis)


def shape(x, name=None):
    """Runtime shape as an int32 tensor (reference shape op)."""
    return Tensor(jnp.asarray(x.shape, jnp.int32))


def multiplex(inputs, index, name=None):
    """Row-wise selection across candidate tensors (reference multiplex:
    out[i] = inputs[index[i]][i])."""
    ts = [t if isinstance(t, Tensor) else Tensor(jnp.asarray(t))
          for t in inputs]
    idx = index if isinstance(index, Tensor) else Tensor(jnp.asarray(index))

    def impl(iv, *vals):
        stacked = jnp.stack(vals)               # [k, B, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[iv.reshape(-1).astype(jnp.int32), rows]
    return op_call("multiplex", impl, idx, *ts)


def reduce_as(x, target, name=None):
    """Sum-reduce x down to target's shape (reference reduce_as — the
    broadcast transpose)."""
    def impl(v, t):
        extra = v.ndim - t.ndim
        out = v.sum(axis=tuple(range(extra))) if extra else v
        axes = tuple(i for i, (a, b) in enumerate(zip(out.shape, t.shape))
                     if a != b and b == 1)
        if axes:
            out = out.sum(axis=axes, keepdims=True)
        return out
    return op_call("reduce_as", impl, x, target)


__all__ += ["unflatten", "diagonal_scatter", "slice_scatter", "reverse",
            "shape", "multiplex", "reduce_as"]
