"""TPU device queries: memory stats from the PJRT runtime.

Analog of paddle.device.cuda memory stats backed by
paddle/phi/core/memory/stats.h — here XLA owns HBM, so stats come from
jax device memory introspection.
"""
from __future__ import annotations

import jax

__all__ = ["device_count", "memory_allocated", "max_memory_allocated",
           "memory_reserved", "max_memory_reserved", "get_device_properties",
           "synchronize", "empty_cache"]


def device_count():
    return len([d for d in jax.devices() if d.platform == "tpu"]) or jax.device_count()


def _stats(device=None):
    dev = jax.devices()[device if isinstance(device, int) else 0]
    try:
        return dev.memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None):
    return int(_stats(device).get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    s = _stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_reserved(device=None):
    s = _stats(device)
    return int(s.get("bytes_reserved", s.get("bytes_in_use", 0)))


def max_memory_reserved(device=None):
    return max_memory_allocated(device)


def get_device_properties(device=None):
    dev = jax.devices()[device if isinstance(device, int) else 0]
    class _Props:
        name = getattr(dev, "device_kind", str(dev))
        total_memory = int(_stats(device).get("bytes_limit", 0))
        multi_processor_count = getattr(dev, "core_count", 1)
    return _Props()


def synchronize(device=None):
    from ..core.device import synchronize as s
    s()


def empty_cache():
    pass
