"""paddle.device.cuda compat shim — maps onto the TPU backend so reference
scripts using cuda memory/stream APIs run unmodified."""
from __future__ import annotations

from .tpu import (  # noqa: F401
    device_count, memory_allocated, max_memory_allocated, memory_reserved,
    max_memory_reserved, get_device_properties, synchronize, empty_cache,
)
from ..core.device import Stream, Event, current_stream  # noqa: F401


def stream_guard(stream):
    class _Guard:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False
    return _Guard()
