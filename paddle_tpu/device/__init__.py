"""paddle.device parity namespace."""
from __future__ import annotations

from ..core.device import (  # noqa: F401
    set_device, get_device, device_count, get_all_device_type,
    is_compiled_with_cuda, is_compiled_with_tpu, is_compiled_with_rocm,
    is_compiled_with_xpu, synchronize, Stream, Event, current_stream,
    local_device_count,
)

from . import cuda  # noqa: F401
from . import tpu  # noqa: F401

__all__ = ["set_device", "get_device", "device_count", "synchronize",
           "Stream", "Event", "current_stream", "cuda", "tpu"]
