"""The op table — declarative single-source specs for the hot op set
(reference paddle/phi/ops/yaml/ops.yaml entries for the same ops).

Each entry is ONE OpSpec; the registry generates the python API, VJP wiring,
AMP-list membership, and the auto-generated OpTest case. `paddle_tpu.tensor`
re-exports these wrappers, so the table is the canonical definition of the
migrated ops (VERDICT r2 item #7: "a new op added by table entry alone gets
API + grad test for free").
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from .registry import OpSpec, OpTest, register_op

__all__ = ["TABLE_OPS"]


def _u(impl, np_ref, name, amp="keep", grad=True, low=-2.0, high=2.0,
       shapes=((4, 8),), rtol=2e-4, atol=1e-5, doc=""):
    """Unary elementwise entry."""
    return OpSpec(name=name, impl=impl, np_ref=np_ref, amp=amp,
                  test=OpTest(shapes=shapes, grad=grad, low=low, high=high,
                              rtol=rtol, atol=atol), doc=doc)


def _b(impl, np_ref, name, amp="keep", grad=True, low=-2.0, high=2.0,
       rtol=2e-4, atol=1e-5):
    """Binary elementwise entry (two same-shape operands)."""
    return OpSpec(name=name, impl=impl, np_ref=np_ref, amp=amp,
                  test=OpTest(shapes=((4, 8), (4, 8)), grad=grad, low=low,
                              high=high, rtol=rtol, atol=atol))


_SPECS = [
    # --- exp/log family (fp32-forced under AMP: reference amp_lists) ------
    _u(jnp.exp, np.exp, "t_exp", amp="deny"),
    _u(jnp.expm1, np.expm1, "t_expm1"),
    _u(jnp.log, np.log, "t_log", amp="deny", low=0.1, high=4.0),
    _u(jnp.log2, np.log2, "t_log2", amp="deny", low=0.1, high=4.0),
    _u(jnp.log10, np.log10, "t_log10", amp="deny", low=0.1, high=4.0),
    _u(jnp.log1p, np.log1p, "t_log1p", low=-0.5, high=4.0),
    _u(jnp.sqrt, np.sqrt, "t_sqrt", amp="deny", low=0.05, high=4.0),
    _u(jax.lax.rsqrt, lambda x: 1.0 / np.sqrt(x), "t_rsqrt", amp="deny",
       low=0.05, high=4.0),
    _u(jnp.square, np.square, "t_square", amp="deny"),
    _u(lambda x: 1.0 / x, lambda x: 1.0 / x, "t_reciprocal",
       low=0.2, high=4.0),
    # --- trig / hyperbolic -----------------------------------------------
    _u(jnp.sin, np.sin, "t_sin"),
    _u(jnp.cos, np.cos, "t_cos"),
    _u(jnp.tan, np.tan, "t_tan", low=-1.0, high=1.0),
    _u(jnp.arcsin, np.arcsin, "t_asin", low=-0.9, high=0.9),
    _u(jnp.arccos, np.arccos, "t_acos", low=-0.9, high=0.9),
    _u(jnp.arctan, np.arctan, "t_atan"),
    _u(jnp.sinh, np.sinh, "t_sinh"),
    _u(jnp.cosh, np.cosh, "t_cosh"),
    _u(jnp.tanh, np.tanh, "t_tanh"),
    _u(jnp.arcsinh, np.arcsinh, "t_asinh"),
    _u(jnp.arctanh, np.arctanh, "t_atanh", low=-0.9, high=0.9),
    # --- rounding / sign (non-differentiable) -----------------------------
    _u(jnp.floor, np.floor, "t_floor", grad=False),
    _u(jnp.ceil, np.ceil, "t_ceil", grad=False),
    _u(jnp.sign, np.sign, "t_sign", grad=False),
    _u(jnp.abs, np.abs, "t_abs", low=0.2, high=3.0),  # keep away from 0 kink
    # --- special ----------------------------------------------------------
    _u(jax.scipy.special.erf, np.vectorize(_math.erf), "t_erf", amp="deny"),
    _u(jax.nn.sigmoid, lambda x: 1 / (1 + np.exp(-x)), "t_sigmoid"),
    _u(jax.nn.softplus, lambda x: np.log1p(np.exp(x)), "t_softplus"),
    _u(jax.nn.silu, lambda x: x / (1 + np.exp(-x)), "t_silu"),
    _u(lambda x: jax.nn.gelu(x, approximate=False),
       lambda x: 0.5 * x * (1 + np.vectorize(__import__("math").erf)(x / np.sqrt(2))),
       "t_gelu", rtol=5e-4, atol=5e-5),
    _u(lambda x: jnp.maximum(x, 0), lambda x: np.maximum(x, 0), "t_relu",
       low=0.2, high=3.0),
    # --- binary -----------------------------------------------------------
    _b(jnp.add, np.add, "t_add"),
    _b(jnp.subtract, np.subtract, "t_subtract"),
    _b(jnp.multiply, np.multiply, "t_multiply"),
    _b(jnp.divide, np.divide, "t_divide", low=0.5, high=3.0),
    _b(jnp.maximum, np.maximum, "t_maximum", grad=False),
    _b(jnp.minimum, np.minimum, "t_minimum", grad=False),
    _b(jnp.arctan2, np.arctan2, "t_atan2", low=0.5, high=3.0),
    OpSpec(name="t_matmul", impl=lambda x, y: x @ y,
           np_ref=lambda x, y: x @ y, amp="allow",
           test=OpTest(shapes=((4, 8), (8, 4)), grad=True)),
    # --- reductions -------------------------------------------------------
    OpSpec(name="t_sum", impl=jnp.sum, np_ref=np.sum, amp="deny",
           test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_mean", impl=jnp.mean, np_ref=np.mean, amp="deny",
           test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_logsumexp",
           impl=jax.scipy.special.logsumexp,
           np_ref=lambda x: np.log(np.sum(np.exp(x))), amp="deny",
           test=OpTest(shapes=((4, 8),), grad=True)),
    # --- softmax family ---------------------------------------------------
    OpSpec(name="t_softmax", impl=lambda x: jax.nn.softmax(x, axis=-1),
           np_ref=lambda x: np.exp(x - x.max(-1, keepdims=True))
           / np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True),
           amp="deny", test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_log_softmax", impl=lambda x: jax.nn.log_softmax(x, axis=-1),
           np_ref=lambda x: x - x.max(-1, keepdims=True)
           - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True)),
           amp="deny", test=OpTest(shapes=((4, 8),), grad=True)),
    # --- custom-vjp demo: identity with scaled gradient (tests the
    #     custom_vjp wiring end to end through the table) ------------------
    OpSpec(name="t_grad_x2",
           impl=lambda x: x * 1.0,
           np_ref=lambda x: x,
           custom_vjp=(lambda x: (x * 1.0, None),
                       lambda res, g: (2.0 * g,)),
           test=OpTest(shapes=((4, 8),), grad=False)),
    # --- round-3 widening: comparison / logical (nondiff) -----------------
    _b(jnp.equal, np.equal, "t_equal", grad=False),
    _b(jnp.not_equal, np.not_equal, "t_not_equal", grad=False),
    _b(jnp.greater, np.greater, "t_greater", grad=False),
    _b(jnp.greater_equal, np.greater_equal, "t_greater_equal", grad=False),
    _b(jnp.less, np.less, "t_less", grad=False),
    _b(jnp.less_equal, np.less_equal, "t_less_equal", grad=False),
    _b(jnp.logical_and, np.logical_and, "t_logical_and", grad=False),
    _b(jnp.logical_or, np.logical_or, "t_logical_or", grad=False),
    _b(jnp.logical_xor, np.logical_xor, "t_logical_xor", grad=False),
    _u(jnp.logical_not, np.logical_not, "t_logical_not", grad=False),
    # --- more elementwise --------------------------------------------------
    _u(jnp.rint, np.rint, "t_rint", grad=False),
    _u(jnp.trunc, np.trunc, "t_trunc", grad=False),
    _u(jnp.cbrt, np.cbrt, "t_cbrt", low=0.2, high=4.0),
    _u(jnp.exp2, np.exp2, "t_exp2"),
    _u(jax.scipy.special.erfc, np.vectorize(_math.erfc), "t_erfc"),
    _u(jnp.deg2rad, np.deg2rad, "t_deg2rad"),
    _u(jnp.rad2deg, np.rad2deg, "t_rad2deg"),
    _b(jnp.hypot, np.hypot, "t_hypot", low=0.5, high=3.0),
    _b(jnp.logaddexp, np.logaddexp, "t_logaddexp"),
    _b(jnp.copysign, np.copysign, "t_copysign", grad=False),
    _b(jnp.nextafter, np.nextafter, "t_nextafter", grad=False),
    _b(jnp.fmod, np.fmod, "t_fmod", grad=False, low=0.5, high=3.0),
    # --- reductions with kwargs --------------------------------------------
    OpSpec(name="t_amax", impl=lambda x: jnp.max(x, axis=-1),
           np_ref=lambda x: np.max(x, axis=-1),
           test=OpTest(shapes=((4, 8),), grad=False)),
    OpSpec(name="t_amin", impl=lambda x: jnp.min(x, axis=-1),
           np_ref=lambda x: np.min(x, axis=-1),
           test=OpTest(shapes=((4, 8),), grad=False)),
    OpSpec(name="t_prod", impl=jnp.prod, np_ref=np.prod, amp="deny",
           test=OpTest(shapes=((2, 4),), grad=True, low=0.5, high=1.5)),
    OpSpec(name="t_var", impl=jnp.var, np_ref=np.var, amp="deny",
           test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_std", impl=jnp.std, np_ref=np.std, amp="deny",
           test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_cumsum", impl=lambda x: jnp.cumsum(x, axis=-1),
           np_ref=lambda x: np.cumsum(x, axis=-1), amp="deny",
           test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_cumprod", impl=lambda x: jnp.cumprod(x, axis=-1),
           np_ref=lambda x: np.cumprod(x, axis=-1),
           test=OpTest(shapes=((4, 8),), grad=True, low=0.5, high=1.5)),
    # --- shape / index -----------------------------------------------------
    OpSpec(name="t_transpose2d", impl=lambda x: jnp.swapaxes(x, -1, -2),
           np_ref=lambda x: np.swapaxes(x, -1, -2),
           test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_flip", impl=lambda x: jnp.flip(x, axis=-1),
           np_ref=lambda x: np.flip(x, axis=-1),
           test=OpTest(shapes=((4, 8),), grad=True)),
    OpSpec(name="t_argmax", impl=lambda x: jnp.argmax(x, axis=-1),
           np_ref=lambda x: np.argmax(x, axis=-1), nondiff=True,
           test=OpTest(shapes=((4, 8),), grad=False)),
    OpSpec(name="t_argmin", impl=lambda x: jnp.argmin(x, axis=-1),
           np_ref=lambda x: np.argmin(x, axis=-1), nondiff=True,
           test=OpTest(shapes=((4, 8),), grad=False)),
    OpSpec(name="t_sort", impl=lambda x: jnp.sort(x, axis=-1),
           np_ref=lambda x: np.sort(x, axis=-1),
           test=OpTest(shapes=((4, 8),), grad=False)),
]


TABLE_OPS = {spec.name: register_op(spec) for spec in _SPECS}


def __getattr__(name):
    if name in TABLE_OPS:
        return TABLE_OPS[name]
    raise AttributeError(name)
