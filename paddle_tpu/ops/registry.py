"""Single-source op registry — the ops.yaml analog (VERDICT r2 item #7).

Reference: paddle/phi/ops/yaml/ops.yaml:8-18 — one declarative entry per op
drives the generated C++ API, dygraph node, VJP wiring, AMP behavior, and
the op's unit test. Here the same single source is an `OpSpec` dataclass:

    register_op(OpSpec(
        name="exp", impl=jnp.exp, np_ref=np.exp, amp="allow",
        test=OpTest(shapes=[(4, 8)], grad=True)))

and from that one entry the registry derives
  * the public python wrapper (dispatch through op_call → kernel registry,
    AMP hook, autograd tape — the eager_gen.py-generated-function analog),
  * VJP availability (jax.vjp over impl; or an explicit custom_vjp pair),
  * the AMP white/black list membership (amp= "allow" | "deny" | "keep"),
  * a generated OpTest case (tests/test_op_registry.py iterates
    `all_specs()` and runs eager + jit + grad checks) — add an op by table
    entry alone and its API + test exist.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..core.dispatch import op_call

__all__ = ["OpSpec", "OpTest", "register_op", "get_spec", "all_specs", "api"]


@dataclasses.dataclass
class OpTest:
    """Test spec: how to auto-generate the OpTest case for an op."""
    shapes: Sequence[Tuple[int, ...]] = ((4, 8),)   # one array per shape
    dtype: str = "float32"
    kwargs: dict = dataclasses.field(default_factory=dict)
    grad: bool = True                    # numeric-vs-analytic grad check
    low: float = -2.0                    # sample range (avoid domain edges)
    high: float = 2.0
    rtol: float = 2e-4
    atol: float = 1e-5
    grad_eps: float = 1e-3


@dataclasses.dataclass
class OpSpec:
    name: str
    impl: Callable                        # jax-level implementation
    np_ref: Optional[Callable] = None     # numpy reference (None → skip test)
    amp: str = "keep"                     # "allow" | "deny" | "keep"
    nondiff: bool = False
    custom_vjp: Optional[Tuple[Callable, Callable]] = None  # (fwd, bwd)
    test: Optional[OpTest] = None
    doc: str = ""


_REGISTRY: Dict[str, OpSpec] = {}
_WRAPPERS: Dict[str, Callable] = {}


def register_op(spec: OpSpec) -> Callable:
    """Register a spec; returns the generated public wrapper."""
    if spec.name in _REGISTRY:
        raise ValueError(f"op {spec.name!r} already registered")
    impl = spec.impl
    if spec.custom_vjp is not None:
        wrapped = jax.custom_vjp(impl)
        wrapped.defvjp(*spec.custom_vjp)
        impl = wrapped
        spec = dataclasses.replace(spec, impl=impl)
    _REGISTRY[spec.name] = spec

    def wrapper(*args, name=None, **kwargs):
        return op_call(spec.name, impl, *args, nondiff=spec.nondiff, **kwargs)

    wrapper.__name__ = spec.name
    wrapper.__qualname__ = spec.name
    wrapper.__doc__ = spec.doc or f"{spec.name} (registry-generated wrapper)"
    wrapper.__op_spec__ = spec
    _WRAPPERS[spec.name] = wrapper

    if spec.amp in ("allow", "deny"):
        from ..amp.auto_cast import WHITE_LIST, BLACK_LIST
        (WHITE_LIST if spec.amp == "allow" else BLACK_LIST).add(spec.name)
    return wrapper


def get_spec(name: str) -> OpSpec:
    return _REGISTRY[name]


def all_specs() -> List[OpSpec]:
    return list(_REGISTRY.values())


def api(name: str) -> Callable:
    """Fetch the canonical wrapper register_op generated."""
    return _WRAPPERS[name]
