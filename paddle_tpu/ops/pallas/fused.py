"""Fused Pallas TPU kernels: rms_norm (fwd+bwd) and the AdamW update.

SURVEY §2.1 kernel north star (the reference fuses these in CUDA:
paddle/phi/kernels/fusion/ fused_rms_norm, gpu/adamw_kernel.cu). XLA fuses
elementwise chains on its own; these kernels exist to (a) pin the fusion
(one VMEM round trip per row regardless of surrounding graph) and (b) keep
the fp32 statistics/moments math inside the kernel while params stream
through in bf16.

rms_norm: rows [N, H]; forward saves inv_rms for a cheap backward.
adamw: one kernel updates (p, m, v) in fp32 math with decoupled weight
decay, reading the bias-corrected step size from SMEM scalars.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["rms_norm", "adamw_update", "softmax", "layer_norm",
           "rms_norm_ref", "layer_norm_ref", "softmax_ref",
           "adamw_update_ref"]

# jnp references (graftlint PAR001: the kernel module itself exports the
# fallback/oracle implementations its parity tests pair against).  The
# norm refs are shared with the functional API — one source of truth.
from ...nn.functional.norm import layer_norm_ref, rms_norm_ref  # noqa: F401,E402


def softmax_ref(x, axis=-1):
    """jnp reference for the fused `softmax` kernel (last-axis case)."""
    return jax.nn.softmax(x, axis=axis)


def adamw_update_ref(p, g, m, v, *, lr, beta1=0.9, beta2=0.999, eps=1e-8,
                     weight_decay=0.01, step=None, bias1=None, bias2=None):
    """jnp reference for `adamw_update` (same signature sans `interpret`):
    fp32 moment math, decoupled weight decay, params back in p.dtype."""
    if bias1 is None:
        bias1 = 1.0 - beta1 ** step
        bias2 = 1.0 - beta2 ** step
    gf = g.astype(jnp.float32)
    nm = beta1 * m.astype(jnp.float32) + (1.0 - beta1) * gf
    nv = beta2 * v.astype(jnp.float32) + (1.0 - beta2) * gf * gf
    mh = nm / bias1
    vh = nv / bias2
    pf = p.astype(jnp.float32)
    np_ = pf - lr * (mh / (jnp.sqrt(vh) + eps) + weight_decay * pf)
    return np_.astype(p.dtype), nm, nv


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------
def _rms_fwd_kernel(x_ref, w_ref, o_ref, inv_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)          # [bn, H]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    o_ref[...] = (x * inv * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)
    inv_ref[...] = inv


def _rms_bwd_kernel(x_ref, w_ref, inv_ref, g_ref, dx_ref, dw_ref, dw_scr, *,
                    eps, num_blocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    inv = inv_ref[...]                           # [bn, 1]
    xhat = x * inv
    gw = g * w
    # dx = inv * (gw - xhat * mean(gw * xhat))
    m = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (inv * (gw - xhat * m)).astype(dx_ref.dtype)
    dw_scr[...] += jnp.sum(g * xhat, axis=0, keepdims=True)

    @pl.when(i == num_blocks - 1)
    def _fin():
        dw_ref[...] = dw_scr[...].astype(dw_ref.dtype)


def _rms_block_rows(n, h):
    bn = max(8, min(256, n))
    while n % bn != 0:
        bn //= 2
    return max(bn, 1)


@functools.lru_cache(maxsize=8)
def _make_rms(eps: float, interpret: bool):
    @jax.custom_vjp
    def op(x, w):
        o, _ = fwd(x, w)
        return o

    def fwd(x, w):
        n, h = x.shape
        bn = _rms_block_rows(n, h)
        o, inv = pl.pallas_call(
            functools.partial(_rms_fwd_kernel, eps=eps),
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((1, h), lambda i: (0, 0))],
            out_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                       pl.BlockSpec((bn, 1), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                       jax.ShapeDtypeStruct((n, 1), jnp.float32)],
            interpret=interpret,
        )(x, w.reshape(1, h))
        return o, (x, w, inv)

    def bwd(res, g):
        x, w, inv = res
        n, h = x.shape
        bn = _rms_block_rows(n, h)
        dx, dw = pl.pallas_call(
            functools.partial(_rms_bwd_kernel, eps=eps, num_blocks=n // bn),
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((1, h), lambda i: (0, 0)),
                      pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                      pl.BlockSpec((bn, h), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                       pl.BlockSpec((1, h), lambda i: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                       jax.ShapeDtypeStruct((1, h), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((1, h), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(x, w.reshape(1, h), inv, g)
        return dx, dw.reshape(w.shape).astype(w.dtype)

    op.defvjp(fwd, bwd)
    return op


def rms_norm(x, weight, eps=1e-6, interpret=False):
    """Fused RMSNorm over the last dim; x [..., H]. Returns None when the
    shape doesn't tile (dispatch falls back to the jnp impl)."""
    h = x.shape[-1]
    if h % 128 != 0:
        return None
    lead = x.shape[:-1]
    n = 1
    for s in lead:
        n *= s
    if n % 8 != 0:
        return None
    out = _make_rms(float(eps), bool(interpret))(x.reshape(n, h), weight)
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# AdamW fused update
# ---------------------------------------------------------------------------
def _adamw_kernel(p_ref, g_ref, m_ref, v_ref, sc_ref,
                  np_ref, nm_ref, nv_ref):
    # sc: [lr, beta1, beta2, eps, wd, bias1, bias2] in SMEM
    lr = sc_ref[0]
    b1 = sc_ref[1]
    b2 = sc_ref[2]
    eps = sc_ref[3]
    wd = sc_ref[4]
    c1 = sc_ref[5]
    c2 = sc_ref[6]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...]
    v = v_ref[...]
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    mhat = m_new / c1
    vhat = v_new / c2
    upd = mhat / (jnp.sqrt(vhat) + eps) + wd * p
    np_ref[...] = (p - lr * upd).astype(np_ref.dtype)
    nm_ref[...] = m_new
    nv_ref[...] = v_new


@functools.lru_cache(maxsize=4)
def _make_adamw(interpret: bool):
    def call(p, g, m, v, scalars):
        n, h = p.shape
        bn = _rms_block_rows(n, h)
        return pl.pallas_call(
            _adamw_kernel,
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                       pl.BlockSpec((bn, h), lambda i: (i, 0)),
                       pl.BlockSpec((bn, h), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct((n, h), p.dtype),
                       jax.ShapeDtypeStruct((n, h), jnp.float32),
                       jax.ShapeDtypeStruct((n, h), jnp.float32)],
            interpret=interpret,
        )(p, g, m, v, scalars)
    return call


_LANE = 1024


def adamw_update(p, g, m, v, *, lr, beta1=0.9, beta2=0.999, eps=1e-8,
                 weight_decay=0.01, step=None, bias1=None, bias2=None,
                 interpret=False):
    """Fused AdamW step on one flat tensor. m/v are fp32; p any float dtype.
    bias1/bias2 = 1-beta^t correction terms (traced scalars ok). Returns
    (p', m', v') or None when the size doesn't tile."""
    total = p.size
    if total % _LANE != 0 or total < 8 * _LANE:
        return None
    if bias1 is None:
        bias1 = 1.0 - beta1 ** step
        bias2 = 1.0 - beta2 ** step
    shape = p.shape
    rows = total // _LANE
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32), jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32), jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(bias1, jnp.float32), jnp.asarray(bias2, jnp.float32)])
    p2 = p.reshape(rows, _LANE)
    g2 = g.reshape(rows, _LANE)
    m2 = m.reshape(rows, _LANE).astype(jnp.float32)
    v2 = v.reshape(rows, _LANE).astype(jnp.float32)
    np_, nm, nv = _make_adamw(bool(interpret))(p2, g2, m2, v2, scalars)
    return np_.reshape(shape), nm.reshape(shape), nv.reshape(shape)


# ---------------------------------------------------------------------------
# Softmax (last axis)
# ---------------------------------------------------------------------------
def _softmax_fwd_kernel(x_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    o_ref[...] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(o_ref.dtype)


def _softmax_bwd_kernel(o_ref, g_ref, dx_ref):
    o = o_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    s = jnp.sum(g * o, axis=-1, keepdims=True)
    dx_ref[...] = (o * (g - s)).astype(dx_ref.dtype)


@functools.lru_cache(maxsize=4)
def _make_softmax(interpret: bool):
    @jax.custom_vjp
    def op(x):
        return fwd(x)[0]

    def fwd(x):
        n, h = x.shape
        bn = _rms_block_rows(n, h)
        o = pl.pallas_call(
            _softmax_fwd_kernel,
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((bn, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
            interpret=interpret,
        )(x)
        return o, o

    def bwd(o, g):
        n, h = o.shape
        bn = _rms_block_rows(n, h)
        dx = pl.pallas_call(
            _softmax_bwd_kernel,
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((bn, h), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((bn, h), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, h), o.dtype),
            interpret=interpret,
        )(o, g)
        return (dx,)

    op.defvjp(fwd, bwd)
    return op


def softmax(x, interpret=False):
    """Fused last-axis softmax over rows; None when untileable."""
    h = x.shape[-1]
    lead = 1
    for s in x.shape[:-1]:
        lead *= s
    if h % 128 != 0 or lead % 8 != 0 or x.ndim < 2:
        return None
    out = _make_softmax(bool(interpret))(x.reshape(lead, h))
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# LayerNorm (last axis, affine)
# ---------------------------------------------------------------------------
def _ln_fwd_kernel(x_ref, w_ref, b_ref, o_ref, mu_ref, inv_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[...] = (xc * inv * w_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)
    mu_ref[...] = mu
    inv_ref[...] = inv


def _ln_bwd_kernel(x_ref, w_ref, mu_ref, inv_ref, g_ref,
                   dx_ref, dw_ref, db_ref, dw_scr, db_scr, *, num_blocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_scr[...] = jnp.zeros_like(dw_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mu = mu_ref[...]
    inv = inv_ref[...]
    xhat = (x - mu) * inv
    gw = g * w
    m1 = jnp.mean(gw, axis=-1, keepdims=True)
    m2 = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (inv * (gw - m1 - xhat * m2)).astype(dx_ref.dtype)
    dw_scr[...] += jnp.sum(g * xhat, axis=0, keepdims=True)
    db_scr[...] += jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == num_blocks - 1)
    def _fin():
        dw_ref[...] = dw_scr[...]
        db_ref[...] = db_scr[...]


@functools.lru_cache(maxsize=8)
def _make_layer_norm(eps: float, interpret: bool):
    @jax.custom_vjp
    def op(x, w, b):
        o, _ = fwd(x, w, b)
        return o

    def fwd(x, w, b):
        n, h = x.shape
        bn = _rms_block_rows(n, h)
        o, mu, inv = pl.pallas_call(
            functools.partial(_ln_fwd_kernel, eps=eps),
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((1, h), lambda i: (0, 0)),
                      pl.BlockSpec((1, h), lambda i: (0, 0))],
            out_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                       pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                       pl.BlockSpec((bn, 1), lambda i: (i, 0))],
            out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                       jax.ShapeDtypeStruct((n, 1), jnp.float32),
                       jax.ShapeDtypeStruct((n, 1), jnp.float32)],
            interpret=interpret,
        )(x, w.reshape(1, h), b.reshape(1, h))
        return o, (x, w, b, mu, inv)

    def bwd(res, g):
        x, w, b, mu, inv = res
        n, h = x.shape
        bn = _rms_block_rows(n, h)
        dx, dw, db = pl.pallas_call(
            functools.partial(_ln_bwd_kernel, num_blocks=n // bn),
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                      pl.BlockSpec((1, h), lambda i: (0, 0)),
                      pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                      pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                      pl.BlockSpec((bn, h), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((bn, h), lambda i: (i, 0)),
                       pl.BlockSpec((1, h), lambda i: (0, 0)),
                       pl.BlockSpec((1, h), lambda i: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((n, h), x.dtype),
                       jax.ShapeDtypeStruct((1, h), jnp.float32),
                       jax.ShapeDtypeStruct((1, h), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((1, h), jnp.float32),
                            pltpu.VMEM((1, h), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(x, w.reshape(1, h), mu, inv, g)
        return dx, dw.reshape(w.shape).astype(w.dtype), \
            db.reshape(b.shape).astype(b.dtype)

    op.defvjp(fwd, bwd)
    return op


def layer_norm(x, weight, bias, eps=1e-5, interpret=False):
    """Fused affine LayerNorm over the last dim; None when untileable."""
    h = x.shape[-1]
    lead = 1
    for s in x.shape[:-1]:
        lead *= s
    if h % 128 != 0 or lead % 8 != 0 or x.ndim < 2:
        return None
    out = _make_layer_norm(float(eps), bool(interpret))(
        x.reshape(lead, h), weight, bias)
    return out.reshape(x.shape)
