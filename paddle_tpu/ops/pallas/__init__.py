"""Pallas TPU kernel overrides.

The PD_REGISTER_KERNEL(..., GPU, ...) analog: importing this module registers
Pallas implementations for hot ops under the same op names the functional API
dispatches through (kernel_registry.h:196 → core/dispatch.py registry).
Registration is TPU-only; on CPU the jnp defaults run (tests exercise the
kernels via interpret=True).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import register_kernel
from . import _compat  # noqa: F401  (pltpu.CompilerParams alias, jax<=0.4)
from . import flash_attention as fa_mod
from . import paged_attention as pa_mod

__all__ = ["register_all", "flash_attention",
           "ragged_paged_attention_decode"]

flash_attention = fa_mod.flash_attention
ragged_paged_attention_decode = pa_mod.ragged_paged_attention_decode


# the kernel module owns its jnp reference (graftlint PAR001: every Pallas
# kernel pairs with a `*_ref` in its own module)
_naive_sdpa = lambda q, k, v, causal: fa_mod.flash_attention_ref(
    q, k, v, causal=causal)


def _softmax_pallas(x, *, axis=-1, cast_dtype=None):
    from . import fused
    from ... import flags as _flags
    if cast_dtype is not None:
        x = x.astype(cast_dtype)
    # flag read at CALL time so toggling works after first registration
    if _flags.get_flag("use_pallas_norm_kernels") and axis in (-1, x.ndim - 1):
        out = fused.softmax(x)
        if out is not None:
            return out
    return jax.nn.softmax(x, axis=axis)


def _layer_norm_pallas(x, *rest, n_axes=1, epsilon=1e-5):
    from . import fused
    from ... import flags as _flags
    if _flags.get_flag("use_pallas_norm_kernels") and n_axes == 1 \
            and len(rest) == 2:
        out = fused.layer_norm(x, rest[0], rest[1], eps=epsilon)
        if out is not None:
            return out
    # flag off / unaffine / multi-axis / untileable: the shared jnp fallback
    from ...nn.functional.norm import layer_norm_ref
    return layer_norm_ref(x, rest[0] if rest else None,
                          rest[1] if len(rest) > 1 else None, n_axes, epsilon)


def _rms_norm_pallas(x, *rest, epsilon=1e-6):
    from . import fused
    if rest:
        out = fused.rms_norm(x, rest[0], eps=epsilon)
        if out is not None:
            return out
    # unweighted or untileable: the shared jnp fallback (XLA fuses it anyway)
    from ...nn.functional.norm import rms_norm_ref
    return rms_norm_ref(x, rest[0] if rest else None, epsilon)


def _fa_varlen(q, k, v, seg, causal=False, rate=0.0, seed=None):
    """Segment-masked (varlen) flash attention, optionally with in-kernel
    dropout; None on unsupported shapes so the caller's block-diagonal XLA
    fallback runs."""
    return fa_mod.flash_attention(q, k, v, causal=causal, segment_ids=seg,
                                  dropout_rate=rate, dropout_seed=seed)


def _fa_plain(q, k, v):
    out = fa_mod.flash_attention(q, k, v, causal=False)
    return out if out is not None else _naive_sdpa(q, k, v, False)


def _fa_dropout(q, k, v, seed, rate=0.1, causal=False):
    """Attention-probability dropout INSIDE the flash kernel (the mask is
    regenerated per block from `seed`, never materialized) — keeps
    dropout-training attention off the [B,H,S,S]-materializing XLA path.
    Falls back to the fused-softmax XLA path on unsupported shapes."""
    out = fa_mod.flash_attention(q, k, v, causal=causal, dropout_rate=rate,
                                 dropout_seed=seed)
    if out is not None:
        return out
    from ...nn.functional.attention import _sdpa_ref
    key = jax.random.PRNGKey(jnp.asarray(seed, jnp.int32))
    return _sdpa_ref(q, k, v, dropout=rate, causal=causal, dropout_key=key)


def _fa_causal(q, k, v):
    out = fa_mod.flash_attention(q, k, v, causal=True)
    return out if out is not None else _naive_sdpa(q, k, v, True)


_registered = [False]


def register_all(force=False):
    """Register Pallas overrides (TPU backend only unless force)."""
    if _registered[0]:
        return
    try:
        on_tpu = any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        on_tpu = False
    if not (on_tpu or force):
        return
    register_kernel("flash_attention", impl="pallas")(_fa_plain)
    register_kernel("flash_attention_causal", impl="pallas")(_fa_causal)
    register_kernel("flash_attention_dropout", impl="pallas")(_fa_dropout)
    register_kernel("rms_norm", impl="pallas")(_rms_norm_pallas)
    register_kernel("flash_attention_varlen", impl="pallas")(_fa_varlen)
    # softmax/layer_norm kernels are opt-in (FLAGS_use_pallas_norm_kernels,
    # checked at CALL time inside the impls): XLA's own fusion measured
    # faster inside full models on v5e (bench r3: ViT-L 239→211 img/s)
    register_kernel("softmax", impl="pallas")(_softmax_pallas)
    register_kernel("layer_norm", impl="pallas")(_layer_norm_pallas)
    from .fused import adamw_update

    def _adamw_gated(*args, **kw):
        # opt-in (FLAGS_use_pallas_adamw, read at CALL time): XLA's own
        # fused elementwise chain measured ~2% faster end-to-end on v5e
        # (round-4 ablation H); None routes the optimizer to its jnp path
        from ... import flags as _flags
        if not _flags.get_flag("use_pallas_adamw"):
            return None
        return adamw_update(*args, **kw)

    register_kernel("adamw_fused", impl="pallas")(_adamw_gated)
    _registered[0] = True
