"""Pallas TPU ragged paged attention (decode shape).

The TPU-native analog of the reference's `block_multihead_attention`
serving kernel (paddle/phi/kernels/fusion/gpu/block_multi_head_attention*)
in the shape Ragged Paged Attention (arxiv 2604.15464) describes: the KV
cache lives in fixed-size PAGES of `page_size` tokens, each sequence owns a
per-sequence page table, and the kernel's grid walks each query's OWN page
list — a ragged batch of mixed-length sequences therefore spends zero FLOPs
(and zero DMA beyond one clamped dummy fetch) on padding to the longest
sequence.

Layout (lane-tiled — no 128x padding cliffs like PERF.md §7.2):

  q          [S, Hq, D]          one query token per active sequence slot
  k_pages    [Hkv, NP, ps, D]    page-pooled keys; last two dims are the
  v_pages    [Hkv, NP, ps, D]    (sublane, lane) tile => D=128-friendly
  page_table [S, P] int32        physical page of each logical page slot
  lengths    [S]   int32         valid KV tokens per slot (0 = inactive)

Grid: (S, Hkv, P) with the page dim innermost ("arbitrary" semantics) so
the per-slot online-softmax scratch survives across a sequence's pages.
The page table and lengths ride scalar prefetch
(`pltpu.PrefetchScalarGridSpec`), so the K/V BlockSpec index maps resolve
the PHYSICAL page to DMA before the kernel body runs — the indirection
costs no kernel time.  GQA is native: the q block for grid step (s, h) is
the `Hq // Hkv` query heads sharing kv head h, and K/V pages are fetched
once per kv head, never materialized per q head.

Pages past a sequence's length are skipped via `pl.when` (their table
entries are clamped to a valid page id by the cache manager, so the
speculative DMA stays in bounds); the final page is mask-tailed inside the
kernel.  A slot with length 0 produces exact zeros.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _compat  # noqa: F401  (pltpu.CompilerParams alias, jax<=0.4)

__all__ = ["ragged_paged_attention_decode", "paged_attention_decode_ref",
           "paged_gather_kv", "paged_gather_scales"]

NEG_INF = -1e30


def _attend_page(q, k, v, i, length, page_size, sm_scale,
                 m_scr, l_scr, acc_scr):
    """One online-softmax update over one (already dequantized, f32) K/V
    page — shared by the plain and fused-dequant kernel bodies so the
    accumulator math can never drift between them."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale         # [rep, ps]
    pos = i * page_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    s = jnp.where(pos < length, s, NEG_INF)
    m_prev = m_scr[:]                             # [rep, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = m_new


def _finalize_out(i, n_pages, o_ref, m_scr, l_scr, acc_scr):
    @pl.when(i == n_pages - 1)
    def _finalize():
        l = l_scr[:]
        inv = jnp.where(l > 0.0, 1.0 / jnp.where(l > 0.0, l, 1.0), 0.0)
        o_ref[0] = (acc_scr[:] * inv).astype(o_ref.dtype)


def _init_scratch(i, m_scr, l_scr, acc_scr):
    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, page_size, sm_scale):
    b = pl.program_id(0)          # sequence slot
    i = pl.program_id(2)          # logical page index (innermost, reduction)
    n_pages = pl.num_programs(2)
    _init_scratch(i, m_scr, l_scr, acc_scr)
    length = len_ref[b]

    @pl.when(i * page_size < length)
    def _body():
        _attend_page(q_ref[0].astype(jnp.float32),
                     k_ref[0, 0].astype(jnp.float32),
                     v_ref[0, 0].astype(jnp.float32),
                     i, length, page_size, sm_scale, m_scr, l_scr, acc_scr)

    _finalize_out(i, n_pages, o_ref, m_scr, l_scr, acc_scr)


def _decode_kernel_quant(pt_ref, len_ref, q_ref, k_ref, ks_ref, v_ref,
                         vs_ref, o_ref, m_scr, l_scr, acc_scr, *,
                         page_size, sm_scale):
    """Fused-dequant variant (ROADMAP item 2): K/V pages arrive in their
    int8/fp8 STORAGE dtype plus a per-row f32 absmax scale page, and the
    dequant happens here, on the page tile already resident in VMEM —
    quantized K/V never materialize as an f32 tensor anywhere (DTYPE001
    polices the host-side paths).  The dequant expression mirrors
    ``serving.quant.dequantize_kv`` exactly (astype f32, multiply by the
    broadcast row scale) so the kernel and every jnp gather path see
    identical values for identical stored rows."""
    b = pl.program_id(0)
    i = pl.program_id(2)
    n_pages = pl.num_programs(2)
    _init_scratch(i, m_scr, l_scr, acc_scr)
    length = len_ref[b]

    @pl.when(i * page_size < length)
    def _body():
        k = k_ref[0, 0].astype(jnp.float32) \
            * ks_ref[0, 0].astype(jnp.float32)[:, None]        # [ps, D]
        v = v_ref[0, 0].astype(jnp.float32) \
            * vs_ref[0, 0].astype(jnp.float32)[:, None]
        _attend_page(q_ref[0].astype(jnp.float32), k, v,
                     i, length, page_size, sm_scale, m_scr, l_scr, acc_scr)

    _finalize_out(i, n_pages, o_ref, m_scr, l_scr, acc_scr)


def ragged_paged_attention_decode(q, k_pages, v_pages, page_table, lengths,
                                  sm_scale=None, interpret=False,
                                  out_dtype=None, k_scales=None,
                                  v_scales=None):
    """One attention step per sequence slot over that slot's page list.

    q [S, Hq, D], k_pages/v_pages [Hkv, NP, ps, D], page_table [S, P] int32
    (entries past a sequence's pages must hold any in-range page id),
    lengths [S] int32 -> o [S, Hq, D].  Requires Hq % Hkv == 0.

    out_dtype: output dtype (default q.dtype).  Accumulation is f32 either
    way; pass jnp.float32 with bf16 inputs to read the un-downcast result
    (the parity tests' bf16→f32 bound).

    k_scales/v_scales (both or neither): per-row absmax scale pages
    [Hkv, NP, ps] f32 for int8/fp8-quantized k_pages/v_pages — dequant
    then FUSES into the kernel (each page tile dequantizes in VMEM right
    before its online-softmax update; the f32 K/V never exist outside the
    kernel).  The scale pages ride the same page-table indirection as the
    data pages.
    """
    s_slots, hq, d = q.shape
    hkv, _np_, page_size, _d = k_pages.shape
    n_ptab = page_table.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"num q heads ({hq}) must be a multiple of kv "
                         f"heads ({hkv})")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    rep = hq // hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    grid = (s_slots, hkv, n_ptab)

    def q_idx(b, h, i, pt, lens):
        return (b, h, 0)

    def kv_idx(b, h, i, pt, lens):
        return (h, pt[b, i], 0, 0)

    def sc_idx(b, h, i, pt, lens):
        return (h, pt[b, i], 0)

    kv_spec = pl.BlockSpec((1, 1, page_size, d), kv_idx)
    sc_spec = pl.BlockSpec((1, 1, page_size), sc_idx)
    quant = k_scales is not None
    if quant:
        in_specs = [pl.BlockSpec((1, rep, d), q_idx),
                    kv_spec, sc_spec, kv_spec, sc_spec]
        inputs = (q, k_pages, k_scales, v_pages, v_scales)
        body = _decode_kernel_quant
    else:
        in_specs = [pl.BlockSpec((1, rep, d), q_idx), kv_spec, kv_spec]
        inputs = (q, k_pages, v_pages)
        body = _decode_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, rep, d), q_idx),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, d), jnp.float32),
        ],
    )
    kernel = functools.partial(body, page_size=page_size,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, hq, d),
                                       out_dtype or q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32), *inputs)


def paged_gather_kv(pages, page_table):
    """Gather a slot-major dense view [S, P*ps, Hkv, D] out of the page pool
    (pages [Hkv, NP, ps, D], page_table [S, P]) — the XLA fallback's (and
    the parity tests') dense reconstruction."""
    g = pages[:, page_table]                      # [Hkv, S, P, ps, D]
    hkv, s, p, ps, d = g.shape
    return g.transpose(1, 2, 3, 0, 4).reshape(s, p * ps, hkv, d)


def paged_gather_scales(scales, page_table):
    """Scale-page analog of :func:`paged_gather_kv`: [Hkv, NP, ps] pages +
    [S, P] table -> slot-major [S, P*ps, Hkv] per-row scales."""
    g = scales[:, page_table]                     # [Hkv, S, P, ps]
    hkv, s, p, ps = g.shape
    return g.transpose(1, 2, 3, 0).reshape(s, p * ps, hkv)


def paged_attention_decode_ref(q, k_pages, v_pages, page_table, lengths,
                               sm_scale=None, out_dtype=None, k_scales=None,
                               v_scales=None):
    """jnp reference/fallback with identical semantics to the kernel
    (gathers pages dense, masks positions >= length, zeros length-0 slots;
    with k_scales/v_scales the gathered int8/fp8 rows dequantize by the
    same astype-f32-times-row-scale expression the kernel fuses).
    This is the CPU path the serving engine uses off-TPU."""
    s_slots, hq, d = q.shape
    hkv = k_pages.shape[0]
    page_size = k_pages.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    k = paged_gather_kv(k_pages, page_table)      # [S, T, Hkv, D]
    v = paged_gather_kv(v_pages, page_table)
    if k_scales is not None:
        ks = paged_gather_scales(k_scales, page_table)   # [S, T, Hkv]
        vs = paged_gather_scales(v_scales, page_table)
        k = k.astype(jnp.float32) * ks.astype(jnp.float32)[..., None]
        v = v.astype(jnp.float32) * vs.astype(jnp.float32)[..., None]
        # round to the QUERY's compute dtype before attending: on a bf16
        # engine every jnp consumer (this ref, the chunk/verify gathers)
        # then sees identical rounded rows — the engine's self-exactness
        # across decode/re-prefill paths needs one value per stored row.
        # No-op at f32.  (The fused TPU kernel keeps f32 dequant in VMEM —
        # decode runs ONE impl per engine, so per-engine exactness holds;
        # kernel-vs-jnp agreement stays the §11 argmax-gated caveat.)
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    if hq != hkv:
        repn = hq // hkv
        k = jnp.repeat(k, repn, axis=2)
        v = jnp.repeat(v, repn, axis=2)
    s = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    t_pos = jnp.arange(s.shape[-1])[None, None, :]
    s = jnp.where(t_pos < lengths[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("sht,sthd->shd", p, v.astype(jnp.float32))
    o = jnp.where(lengths[:, None, None] > 0, o, 0.0)
    return o.astype(out_dtype or q.dtype)
