"""Pallas TPU ragged paged attention — ONE kernel for every serving path.

The TPU-native analog of the reference's `block_multihead_attention`
serving kernel (paddle/phi/kernels/fusion/gpu/block_multi_head_attention*)
in the shape Ragged Paged Attention (arxiv 2604.15464) describes: the KV
cache lives in fixed-size PAGES of `page_size` tokens, each sequence owns a
per-sequence page table, and each slot contributes a RAGGED QUERY SEGMENT
`(q_start, q_len, kv_len)` — `q_len` fresh query tokens whose absolute
positions are `q_start .. q_start + q_len - 1`, attending the slot's paged
context under an intra-segment causal mask.  The three serving dispatch
shapes are all special cases of the one grid:

  q_len = 1        decode       (the new token attends everything before it)
  q_len = K+1      spec verify  (pending + K draft tokens, causal between)
  q_len = chunk    chunked prefill (one chunk of the prompt, causal over
                                    cached context + earlier chunk tokens)

so decode, verify, and chunked prefill score through the SAME kernel body
(and, off-TPU, the same `*_ref`) — the impl-uniformity the speculative
losslessness guarantee rests on.

Layout (lane-tiled — no 128x padding cliffs like PERF.md §7.2):

  q          [S, Qmax, Hq, D]    ragged query segments, right-padded to Qmax
  k_pages    [Hkv, NP, ps, D]    page-pooled keys; last two dims are the
  v_pages    [Hkv, NP, ps, D]    (sublane, lane) tile => D=128-friendly
  page_table [S, P] int32        physical page of each logical page slot
  q_start    [S]   int32         absolute position of query 0 per slot
  q_len      [S]   int32         valid queries per slot (0 = inactive)
  kv_len     [S]   int32         total valid KV tokens (incl. the segment)

Grid: (S, Hkv, P) with the page dim innermost ("arbitrary" semantics) so
the per-slot online-softmax scratch survives across a sequence's pages.
The page table and segment descriptors ride scalar prefetch
(`pltpu.PrefetchScalarGridSpec`), so the K/V BlockSpec index maps resolve
the PHYSICAL page to DMA before the kernel body runs — the indirection
costs no kernel time.  GQA is native: the q block for grid step (s, h) is
the `Hq // Hkv` query heads sharing kv head h, and K/V pages are fetched
once per kv head, never materialized per q head.

Pages past a slot's `kv_len` are skipped via `pl.when` (their table entries
are clamped to a valid page id by the cache manager, so the speculative DMA
stays in bounds); partial pages and the causal frontier are mask-tailed
inside the kernel.  Padding query rows (>= q_len) and inactive slots
(q_len = 0) produce exact zeros, matching the reference.

int8/fp8 pages (`k_scales`/`v_scales`) dequantize INSIDE the kernel for
every path — the per-(page, head, token-row) scale pages ride the same
page-table indirection, and the f32 K/V never exist outside VMEM.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _compat  # noqa: F401  (pltpu.CompilerParams alias, jax<=0.4)

__all__ = ["ragged_paged_attention", "ragged_paged_attention_ref",
           "ragged_paged_attention_decode", "paged_attention_decode_ref",
           "paged_gather_kv", "paged_gather_scales"]

NEG_INF = -1e30


def _attend_page(q, k, v, mask, sm_scale, m_scr, l_scr, acc_scr):
    """One online-softmax update over one (already dequantized, f32) K/V
    page — shared by the plain and fused-dequant kernel bodies so the
    accumulator math can never drift between them.  ``q`` is the flattened
    [Qmax*rep, D] query block, ``mask`` the [Qmax*rep, ps] validity of each
    (query row, kv position) pair; a row with no valid position EVER (a
    padding query) keeps m = NEG_INF and l = 0, so the finalizer emits
    exact zeros for it."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale     # [Qmax*rep, ps]
    s = jnp.where(mask, s, NEG_INF)
    m_prev = m_scr[:]                                      # [Qmax*rep, 1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    # re-mask p explicitly: on a row whose every position is masked,
    # exp(NEG_INF - NEG_INF) would be 1, silently averaging garbage V rows
    # into the padding-query output
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m_prev - m_new)
    l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = m_new


def _segment_mask(shape, i, page_size, rep, q_start, q_len, kv_len):
    """[Qmax*rep, ps] validity of page i's positions against the slot's
    ragged segment: kv position `col` is visible to query row `r` (query
    index r // rep) iff it is causally before-or-at that query's absolute
    position, the query is real, and the position holds valid KV."""
    row = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    col = i * page_size + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    qi = row // rep
    return (col <= q_start + qi) & (qi < q_len) & (col < kv_len)


def _finalize_out(i, n_pages, o_ref, m_scr, l_scr, acc_scr):
    @pl.when(i == n_pages - 1)
    def _finalize():
        l = l_scr[:]
        inv = jnp.where(l > 0.0, 1.0 / jnp.where(l > 0.0, l, 1.0), 0.0)
        o_ref[0] = (acc_scr[:] * inv).reshape(o_ref.shape[1:]) \
            .astype(o_ref.dtype)


def _init_scratch(i, m_scr, l_scr, acc_scr):
    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)


def _ragged_kernel(pt_ref, qs_ref, ql_ref, kl_ref, q_ref, k_ref, v_ref,
                   o_ref, m_scr, l_scr, acc_scr, *, page_size, sm_scale,
                   rep):
    b = pl.program_id(0)          # sequence slot
    i = pl.program_id(2)          # logical page index (innermost, reduction)
    n_pages = pl.num_programs(2)
    _init_scratch(i, m_scr, l_scr, acc_scr)
    q_start, q_len, kv_len = qs_ref[b], ql_ref[b], kl_ref[b]

    @pl.when(i * page_size < kv_len)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        qmax = q.shape[0]
        q2 = q.reshape(qmax * rep, q.shape[-1])
        mask = _segment_mask((qmax * rep, page_size), i, page_size, rep,
                             q_start, q_len, kv_len)
        _attend_page(q2, k_ref[0, 0].astype(jnp.float32),
                     v_ref[0, 0].astype(jnp.float32),
                     mask, sm_scale, m_scr, l_scr, acc_scr)

    _finalize_out(i, n_pages, o_ref, m_scr, l_scr, acc_scr)


def _ragged_kernel_quant(pt_ref, qs_ref, ql_ref, kl_ref, q_ref, k_ref,
                         ks_ref, v_ref, vs_ref, o_ref, m_scr, l_scr,
                         acc_scr, *, page_size, sm_scale, rep):
    """Fused-dequant variant: K/V pages arrive in their int8/fp8 STORAGE
    dtype plus a per-row f32 absmax scale page, and the dequant happens
    here, on the page tile already resident in VMEM — quantized K/V never
    materialize as an f32 tensor anywhere (DTYPE001 polices the host-side
    paths).  The dequant expression mirrors ``serving.quant.dequantize_kv``
    exactly (astype f32, multiply by the broadcast row scale) so the kernel
    and every jnp gather path see identical values for identical stored
    rows — on EVERY dispatch path, not just decode."""
    b = pl.program_id(0)
    i = pl.program_id(2)
    n_pages = pl.num_programs(2)
    _init_scratch(i, m_scr, l_scr, acc_scr)
    q_start, q_len, kv_len = qs_ref[b], ql_ref[b], kl_ref[b]

    @pl.when(i * page_size < kv_len)
    def _body():
        k = k_ref[0, 0].astype(jnp.float32) \
            * ks_ref[0, 0].astype(jnp.float32)[:, None]        # [ps, D]
        v = v_ref[0, 0].astype(jnp.float32) \
            * vs_ref[0, 0].astype(jnp.float32)[:, None]
        q = q_ref[0].astype(jnp.float32)
        qmax = q.shape[0]
        q2 = q.reshape(qmax * rep, q.shape[-1])
        mask = _segment_mask((qmax * rep, page_size), i, page_size, rep,
                             q_start, q_len, kv_len)
        _attend_page(q2, k, v, mask, sm_scale, m_scr, l_scr, acc_scr)

    _finalize_out(i, n_pages, o_ref, m_scr, l_scr, acc_scr)


def ragged_paged_attention(q, k_pages, v_pages, page_table, q_start, q_len,
                           kv_len, sm_scale=None, interpret=False,
                           out_dtype=None, k_scales=None, v_scales=None):
    """Ragged-segment paged attention over each slot's page list.

    q [S, Qmax, Hq, D], k_pages/v_pages [Hkv, NP, ps, D], page_table
    [S, P] int32 (entries past a slot's pages must hold any in-range page
    id), q_start/q_len/kv_len [S] int32 -> o [S, Qmax, Hq, D].  Query j of
    slot s sits at absolute position q_start[s] + j and attends kv
    positions <= its own (and < kv_len[s]); rows past q_len[s] — and every
    row of a q_len = 0 slot — come back exactly zero.  Requires
    Hq % Hkv == 0.

    out_dtype: output dtype (default q.dtype).  Accumulation is f32 either
    way; pass jnp.float32 with bf16 inputs to read the un-downcast result
    (the parity tests' bf16→f32 bound).

    k_scales/v_scales (both or neither): per-row absmax scale pages
    [Hkv, NP, ps] f32 for int8/fp8-quantized k_pages/v_pages — dequant
    then FUSES into the kernel (each page tile dequantizes in VMEM right
    before its online-softmax update; the f32 K/V never exist outside the
    kernel).  The scale pages ride the same page-table indirection as the
    data pages.

    Head-sharded (TP) dispatch: every shape here may be the mp-LOCAL
    shard — Hq = nh/tp query heads against Hkv = nkv/tp KV-head pages.
    Nothing in the kernel knows about the mesh: the grid, the GQA
    replication factor (rep = Hq // Hkv), and the block specs all derive
    from the operand shapes, so the tensor-parallel serving engine calls
    the SAME dispatch per rank inside shard_map that the single-chip
    engine calls globally.  Correctness of the local GQA pairing needs
    mp | nkv (then local q head j reads local kv head j // rep, exactly
    the global mapping restricted to rank r's contiguous head block) —
    the divisibility guard below enforces the local ratio, the builder
    (models/llama.build_llama_paged_decode) enforces mp | nkv.
    """
    s_slots, qmax, hq, d = q.shape
    hkv, _np_, page_size, _d = k_pages.shape
    n_ptab = page_table.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"num q heads ({hq}) must be a multiple of kv "
                         f"heads ({hkv})")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    rep = hq // hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    grid = (s_slots, hkv, n_ptab)

    def q_idx(b, h, i, pt, qs, ql, kl):
        return (b, 0, h, 0)

    def kv_idx(b, h, i, pt, qs, ql, kl):
        return (h, pt[b, i], 0, 0)

    def sc_idx(b, h, i, pt, qs, ql, kl):
        return (h, pt[b, i], 0)

    q_spec = pl.BlockSpec((1, qmax, rep, d), q_idx)
    kv_spec = pl.BlockSpec((1, 1, page_size, d), kv_idx)
    sc_spec = pl.BlockSpec((1, 1, page_size), sc_idx)
    quant = k_scales is not None
    if quant:
        in_specs = [q_spec, kv_spec, sc_spec, kv_spec, sc_spec]
        inputs = (q, k_pages, k_scales, v_pages, v_scales)
        body = _ragged_kernel_quant
    else:
        in_specs = [q_spec, kv_spec, kv_spec]
        inputs = (q, k_pages, v_pages)
        body = _ragged_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=in_specs,
        out_specs=q_spec,
        scratch_shapes=[
            pltpu.VMEM((qmax * rep, 1), jnp.float32),
            pltpu.VMEM((qmax * rep, 1), jnp.float32),
            pltpu.VMEM((qmax * rep, d), jnp.float32),
        ],
    )
    kernel = functools.partial(body, page_size=page_size,
                               sm_scale=sm_scale, rep=rep)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, qmax, hq, d),
                                       out_dtype or q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), q_start.astype(jnp.int32),
      q_len.astype(jnp.int32), kv_len.astype(jnp.int32), *inputs)


def paged_gather_kv(pages, page_table):
    """Gather a slot-major dense view [S, P*ps, Hkv, D] out of the page pool
    (pages [Hkv, NP, ps, D], page_table [S, P]) — the XLA fallback's (and
    the parity tests') dense reconstruction."""
    g = pages[:, page_table]                      # [Hkv, S, P, ps, D]
    hkv, s, p, ps, d = g.shape
    return g.transpose(1, 2, 3, 0, 4).reshape(s, p * ps, hkv, d)


def paged_gather_scales(scales, page_table):
    """Scale-page analog of :func:`paged_gather_kv`: [Hkv, NP, ps] pages +
    [S, P] table -> slot-major [S, P*ps, Hkv] per-row scales."""
    g = scales[:, page_table]                     # [Hkv, S, P, ps]
    hkv, s, p, ps = g.shape
    return g.transpose(1, 2, 3, 0).reshape(s, p * ps, hkv)


def ragged_paged_attention_ref(q, k_pages, v_pages, page_table, q_start,
                               q_len, kv_len, sm_scale=None, out_dtype=None,
                               k_scales=None, v_scales=None):
    """jnp reference/fallback with identical semantics to the ragged
    kernel (gathers pages dense, masks causally inside each slot's
    segment, zeros padding query rows and q_len-0 slots; with
    k_scales/v_scales the gathered int8/fp8 rows dequantize by the same
    astype-f32-times-row-scale expression the kernel fuses).  This is the
    CPU path the serving engine dispatches for decode, verify, AND
    chunked prefill — one implementation per engine, every path.  Like
    the kernel it is head-shard agnostic: under TP serving each rank
    passes its mp-local Hq/Hkv shapes and the ref computes that rank's
    heads exactly (same guard, same local GQA pairing)."""
    s_slots, qmax, hq, d = q.shape
    hkv = k_pages.shape[0]
    page_size = k_pages.shape[2]
    if hq % hkv != 0:
        raise ValueError(f"num q heads ({hq}) must be a multiple of kv "
                         f"heads ({hkv})")
    if (k_scales is None) != (v_scales is None):
        raise ValueError("pass both k_scales and v_scales, or neither")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    k = paged_gather_kv(k_pages, page_table)      # [S, T, Hkv, D]
    v = paged_gather_kv(v_pages, page_table)
    if k_scales is not None:
        ks = paged_gather_scales(k_scales, page_table)   # [S, T, Hkv]
        vs = paged_gather_scales(v_scales, page_table)
        k = k.astype(jnp.float32) * ks.astype(jnp.float32)[..., None]
        v = v.astype(jnp.float32) * vs.astype(jnp.float32)[..., None]
        # round to the QUERY's compute dtype before attending: on a bf16
        # engine every jnp consumer then sees identical rounded rows — the
        # engine's self-exactness across decode/verify/chunk/re-prefill
        # paths needs one value per stored row.  No-op at f32.  (The fused
        # TPU kernel keeps f32 dequant in VMEM — each engine runs ONE impl
        # on every path, so per-engine exactness holds.)
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    if hq != hkv:
        repn = hq // hkv
        k = jnp.repeat(k, repn, axis=2)
        v = jnp.repeat(v, repn, axis=2)
    s = jnp.einsum("sqhd,sthd->shqt", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    t_pos = jnp.arange(s.shape[-1])[None, None, None, :]
    qi = jnp.arange(qmax)[None, None, :, None]
    ok = (t_pos <= q_start[:, None, None, None] + qi) \
        & (qi < q_len[:, None, None, None]) \
        & (t_pos < kv_len[:, None, None, None])
    # NEG_INF (not -inf): a fully masked row softmaxes to uniform garbage
    # instead of NaN, and the q_len mask below zeroes it either way
    s = jnp.where(ok, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("shqt,sthd->sqhd", p, v.astype(jnp.float32))
    o = jnp.where(jnp.arange(qmax)[None, :, None, None]
                  < q_len[:, None, None, None], o, 0.0)
    return o.astype(out_dtype or q.dtype)


def ragged_paged_attention_decode(q, k_pages, v_pages, page_table, lengths,
                                  sm_scale=None, interpret=False,
                                  out_dtype=None, k_scales=None,
                                  v_scales=None):
    """Decode-shape convenience wrapper: one query per slot (`q [S, Hq,
    D]`, `lengths [S]` = valid KV INCLUDING the freshly written token) is
    the `q_len = 1` special case of :func:`ragged_paged_attention` — kept
    as an API so callers with a flat decode batch don't hand-build the
    segment descriptors.  A slot with length 0 produces exact zeros."""
    lengths = lengths.astype(jnp.int32)
    o = ragged_paged_attention(
        q[:, None], k_pages, v_pages, page_table,
        jnp.maximum(lengths - 1, 0), (lengths > 0).astype(jnp.int32),
        lengths, sm_scale=sm_scale, interpret=interpret,
        out_dtype=out_dtype, k_scales=k_scales, v_scales=v_scales)
    return o[:, 0]


def paged_attention_decode_ref(q, k_pages, v_pages, page_table, lengths,
                               sm_scale=None, out_dtype=None, k_scales=None,
                               v_scales=None):
    """Decode-shape wrapper over :func:`ragged_paged_attention_ref` — the
    same `q_len = 1` specialization as the kernel-side wrapper, so the
    decode pair stays a pure delegation to the ONE ragged pair."""
    lengths = lengths.astype(jnp.int32)
    o = ragged_paged_attention_ref(
        q[:, None], k_pages, v_pages, page_table,
        jnp.maximum(lengths - 1, 0), (lengths > 0).astype(jnp.int32),
        lengths, sm_scale=sm_scale, out_dtype=out_dtype,
        k_scales=k_scales, v_scales=v_scales)
    return o[:, 0]
