"""Pallas TPU ragged paged attention (decode shape).

The TPU-native analog of the reference's `block_multihead_attention`
serving kernel (paddle/phi/kernels/fusion/gpu/block_multi_head_attention*)
in the shape Ragged Paged Attention (arxiv 2604.15464) describes: the KV
cache lives in fixed-size PAGES of `page_size` tokens, each sequence owns a
per-sequence page table, and the kernel's grid walks each query's OWN page
list — a ragged batch of mixed-length sequences therefore spends zero FLOPs
(and zero DMA beyond one clamped dummy fetch) on padding to the longest
sequence.

Layout (lane-tiled — no 128x padding cliffs like PERF.md §7.2):

  q          [S, Hq, D]          one query token per active sequence slot
  k_pages    [Hkv, NP, ps, D]    page-pooled keys; last two dims are the
  v_pages    [Hkv, NP, ps, D]    (sublane, lane) tile => D=128-friendly
  page_table [S, P] int32        physical page of each logical page slot
  lengths    [S]   int32         valid KV tokens per slot (0 = inactive)

Grid: (S, Hkv, P) with the page dim innermost ("arbitrary" semantics) so
the per-slot online-softmax scratch survives across a sequence's pages.
The page table and lengths ride scalar prefetch
(`pltpu.PrefetchScalarGridSpec`), so the K/V BlockSpec index maps resolve
the PHYSICAL page to DMA before the kernel body runs — the indirection
costs no kernel time.  GQA is native: the q block for grid step (s, h) is
the `Hq // Hkv` query heads sharing kv head h, and K/V pages are fetched
once per kv head, never materialized per q head.

Pages past a sequence's length are skipped via `pl.when` (their table
entries are clamped to a valid page id by the cache manager, so the
speculative DMA stays in bounds); the final page is mask-tailed inside the
kernel.  A slot with length 0 produces exact zeros.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import _compat  # noqa: F401  (pltpu.CompilerParams alias, jax<=0.4)

__all__ = ["ragged_paged_attention_decode", "paged_attention_decode_ref",
           "paged_gather_kv"]

NEG_INF = -1e30


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, page_size, sm_scale):
    b = pl.program_id(0)          # sequence slot
    i = pl.program_id(2)          # logical page index (innermost, reduction)
    n_pages = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]

    @pl.when(i * page_size < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)          # [rep, D]
        k = k_ref[0, 0]                           # [ps, D]
        v = v_ref[0, 0]                           # [ps, D]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale     # [rep, ps]
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_scr[:]                         # [rep, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(i == n_pages - 1)
    def _finalize():
        l = l_scr[:]
        inv = jnp.where(l > 0.0, 1.0 / jnp.where(l > 0.0, l, 1.0), 0.0)
        o_ref[0] = (acc_scr[:] * inv).astype(o_ref.dtype)


def ragged_paged_attention_decode(q, k_pages, v_pages, page_table, lengths,
                                  sm_scale=None, interpret=False,
                                  out_dtype=None):
    """One attention step per sequence slot over that slot's page list.

    q [S, Hq, D], k_pages/v_pages [Hkv, NP, ps, D], page_table [S, P] int32
    (entries past a sequence's pages must hold any in-range page id),
    lengths [S] int32 -> o [S, Hq, D].  Requires Hq % Hkv == 0.

    out_dtype: output dtype (default q.dtype).  Accumulation is f32 either
    way; pass jnp.float32 with bf16 inputs to read the un-downcast result
    (the parity tests' bf16→f32 bound).
    """
    s_slots, hq, d = q.shape
    hkv, _np_, page_size, _d = k_pages.shape
    n_ptab = page_table.shape[1]
    if hq % hkv != 0:
        raise ValueError(f"num q heads ({hq}) must be a multiple of kv "
                         f"heads ({hkv})")
    rep = hq // hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)

    grid = (s_slots, hkv, n_ptab)

    def q_idx(b, h, i, pt, lens):
        return (b, h, 0)

    def kv_idx(b, h, i, pt, lens):
        return (h, pt[b, i], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, rep, d), q_idx),
            pl.BlockSpec((1, 1, page_size, d), kv_idx),
            pl.BlockSpec((1, 1, page_size, d), kv_idx),
        ],
        out_specs=pl.BlockSpec((1, rep, d), q_idx),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, d), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, page_size=page_size,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_slots, hq, d),
                                       out_dtype or q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary")),
        interpret=interpret,
    )(page_table.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages)


def paged_gather_kv(pages, page_table):
    """Gather a slot-major dense view [S, P*ps, Hkv, D] out of the page pool
    (pages [Hkv, NP, ps, D], page_table [S, P]) — the XLA fallback's (and
    the parity tests') dense reconstruction."""
    g = pages[:, page_table]                      # [Hkv, S, P, ps, D]
    hkv, s, p, ps, d = g.shape
    return g.transpose(1, 2, 3, 0, 4).reshape(s, p * ps, hkv, d)


def paged_attention_decode_ref(q, k_pages, v_pages, page_table, lengths,
                               sm_scale=None, out_dtype=None):
    """jnp reference/fallback with identical semantics to the kernel
    (gathers pages dense, masks positions >= length, zeros length-0 slots).
    This is the CPU path the serving engine uses off-TPU."""
    s_slots, hq, d = q.shape
    hkv = k_pages.shape[0]
    page_size = k_pages.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    k = paged_gather_kv(k_pages, page_table)      # [S, T, Hkv, D]
    v = paged_gather_kv(v_pages, page_table)
    if hq != hkv:
        repn = hq // hkv
        k = jnp.repeat(k, repn, axis=2)
        v = jnp.repeat(v, repn, axis=2)
    s = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    t_pos = jnp.arange(s.shape[-1])[None, None, :]
    s = jnp.where(t_pos < lengths[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("sht,sthd->shd", p, v.astype(jnp.float32))
    o = jnp.where(lengths[:, None, None] > 0, o, 0.0)
    return o.astype(out_dtype or q.dtype)
