"""Pallas API compatibility across jax versions.

The kernels target the current `pltpu.CompilerParams` spelling; older jax
builds (<= 0.4.x, including the container's jax_graft toolchain) ship the
same dataclass as `TPUCompilerParams`.  Alias it once here — every module
in this package imports `_compat` before touching pltpu.
"""
from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams") and hasattr(pltpu, "TPUCompilerParams"):
    pltpu.CompilerParams = pltpu.TPUCompilerParams
