"""Pallas TPU flash attention (forward + backward).

The TPU-native replacement for the reference's CUDA FA2 kernel
(paddle/phi/kernels/gpu/flash_attn_kernel.cu + third_party flashattn):
online-softmax tiling so the S×S score matrix never hits HBM.

Layout: [B, S, H, D] at the API (reference flash_attention.py convention);
kernels run per (batch*head) over [BH, S, D] with q-block × k-block tiling.

Forward: FlashAttention-2 style — one pass over K/V blocks per Q block with a
running max/denominator in VMEM scratch; emits O and the per-row logsumexp L.
Backward: two kernels (dKdV accumulating over Q blocks; dQ accumulating over
K blocks) using the saved L and D = rowsum(dO ∘ O).

Grid iteration puts the reduction dim last ("arbitrary" semantics) so output
blocks are revisited with live scratch.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_fwd_kernel_call"]

NEG_INF = -1e30


def _block_sizes(s_q, s_k, d):
    """v5e-measured defaults (round-4 sweep on the 271M llama train step):
    k-blocks of 1024 beat 512 at every config (+6% at S=2048); q-blocks of
    512 win at S<=4k, 1024 at S>=8k (+5% at S=8192).  128-multiple
    fallbacks keep odd shapes tileable."""
    bq_pref = 1024 if s_q >= 8192 else 512
    bq = next((b for b in (bq_pref, 512, 256, 128) if s_q % b == 0 and b <= s_q),
              s_q)
    bk = next((b for b in (1024, 512, 256, 128) if s_k % b == 0 and b <= s_k),
              s_k)
    return bq, bk


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------
def _dropout_mask(seed_f32, qh, i, j, n_i, n_j, shape, rate):
    """Regenerable keep-mask scale for block (qh, i, j): seeds the per-core
    PRNG deterministically so the backward kernels rebuild the identical
    mask without it ever hitting HBM (the same trick the reference's CUDA
    FA uses with its philox offset).  The TPU PRNG takes at most two seed
    words, so the block coordinates mix into one int32 (unique per block:
    i < n_i, j < n_j are grid sizes)."""
    mix = (qh * n_i + i) * n_j + j
    pltpu.prng_seed(jnp.int32(seed_f32), mix)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    thresh = jnp.uint32(int(rate * 4294967296.0))
    keep = bits >= thresh                       # P(keep) = 1 - rate
    return keep.astype(jnp.float32) / (1.0 - rate)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal, sm_scale, block_q,
                block_k, num_k_blocks, offset, has_segments=False,
                dropout_rate=0.0, num_q_blocks=1):
    rest = list(rest)
    qseg_ref = kseg_ref = seed_ref = None
    if has_segments:
        qseg_ref, kseg_ref = rest.pop(0), rest.pop(0)
    if dropout_rate > 0.0:
        seed_ref = rest.pop(0)
    o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    j = pl.program_id(2)  # k-block index (innermost, reduction)
    i = pl.program_id(1)  # q-block index

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: process only blocks with k_start <= q_end
    run = True
    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1 + offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]                      # [block_q, d]
        k = k_ref[0]                      # [block_k, d]
        v = v_ref[0]                      # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids + offset >= k_ids, s, NEG_INF)
        if has_segments:
            qs = qseg_ref[0, :, 0]        # [block_q] (f32 segment ids)
            ks = kseg_ref[0, :, 0]        # [block_k]
            s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
        m_prev = m_scr[:]                 # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)            # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)   # [bq, 1]
        # the softmax DENOMINATOR uses the un-dropped p (dropout applies to
        # the normalized probabilities); only the V accumulation is masked
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        pd = p
        if dropout_rate > 0.0:
            b = pl.program_id(0)
            pd = p * _dropout_mask(seed_ref[0], b, i, j, num_q_blocks,
                                   num_k_blocks, (block_q, block_k),
                                   dropout_rate)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pd.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:]
        inv = jnp.where(l > 0.0, 1.0 / jnp.where(l > 0.0, l, 1.0), 0.0)
        o_ref[0] = (acc_scr[:] * inv).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l, 1e-30))


def _pack_lse(lse3, interpret=False):
    """[bh, s, 1] (tile-padded 1 -> 128 lanes in HBM: 128x memory) ->
    compact [bh, s] via a repack kernel.  A plain squeeze does NOT work:
    XLA lowers it as a bitcast that keeps the padded layout alive — with 24
    saved lse residuals that measured 6 GB of pure padding (the r5 ViT
    OOM).  The grid walks s in fixed-size row CHUNKS (ADVICE r5 #4): a
    full-row block holds ~512·s transient bytes of lane padding in VMEM,
    which overflowed it at s >= ~16k even though the attention kernels
    themselves tile fine there."""
    bh, s, _ = lse3.shape
    chunk = next(b for b in (1024, 512, 256, 128) if s % b == 0)

    def kern(x_ref, o_ref):
        o_ref[0] = x_ref[0][:, 0].reshape(chunk // 128, 128)

    out = pl.pallas_call(
        kern, grid=(bh, s // chunk),
        in_specs=[pl.BlockSpec((1, chunk, 1), lambda b, i: (b, i, 0))],
        out_specs=pl.BlockSpec((1, chunk // 128, 128), lambda b, i: (b, i, 0)),
        out_shape=_sds((bh, s // 128, 128), lse3.dtype, _vma_of(lse3)),
        interpret=interpret,
    )(lse3)
    return out.reshape(bh, s)


def _vma_of(*arrs):
    """Union of manual-axes (shard_map vma) of the inputs: pallas_call
    out_shapes must declare it when the kernel runs inside shard_map."""
    out = frozenset()
    for a in arrs:
        out |= getattr(getattr(a, "aval", None), "vma", frozenset()) or frozenset()
    return out


def _sds(shape, dtype, vma):
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def flash_attention_fwd_kernel_call(q, k, v, causal, sm_scale, interpret=False,
                                    n_q_heads=None, n_kv_heads=None,
                                    segment_ids=None, dropout_rate=0.0,
                                    dropout_seed=None):
    """q: [B*Hq, S, D], k/v: [B*Hkv, S, D] -> (o [B*Hq, Sq, D], lse).

    GQA (n_kv_heads < n_q_heads) is handled in the BlockSpec index maps: the
    kernel reads KV blocks of head h // rep directly from HBM — no
    materialized jnp.repeat of K/V (reference flash_attn_kernel.cu GQA path).
    """
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    hq = n_q_heads or 1
    hkv = n_kv_heads or hq
    rep = hq // hkv
    block_q, block_k = _block_sizes(s_q, s_k, d)
    grid = (bh, s_q // block_q, s_k // block_k)

    def kv_idx(b, i, j):
        return ((b // hq) * hkv + (b % hq) // rep, j, 0)

    has_seg = segment_ids is not None
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, num_k_blocks=s_k // block_k, offset=s_k - s_q,
        has_segments=has_seg, dropout_rate=dropout_rate,
        num_q_blocks=s_q // block_q)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_idx),
        pl.BlockSpec((1, block_k, d), kv_idx),
    ]
    args = [q, k, v]
    if has_seg:
        # segment ids per batch row [B, S] (f32), broadcast over heads.
        # The [B, S, 1] kernel view tile-pads 1 -> 128 lanes, but only as a
        # TRANSIENT around this call (the caller holds compact [B, S]) —
        # TPU Pallas requires the last two block dims (8, 128)-aligned, so
        # a 2-D (1, block) spec is not lowerable.
        seg3 = segment_ids[:, :, None]
        in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b // hq, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b // hq, j, 0)),
        ]
        args += [seg3, seg3]
    if dropout_rate > 0.0:
        in_specs += [pl.BlockSpec(memory_space=pltpu.SMEM)]
        args += [jnp.asarray(dropout_seed, jnp.float32).reshape(1)]
    o, lse3 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, s_q, d), q.dtype, _vma_of(q, k, v)),
            _sds((bh, s_q, 1), jnp.float32, _vma_of(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)
    # COMPACT 2-D lse for the caller: the [bh, s, 1] kernel output tile-pads
    # its last dim 1 -> 128 in HBM (measured 128x, 256 MB per ViT layer);
    # _pack_lse forces a real re-layout (a squeeze is just a bitcast) so
    # saved residuals cost s_q * 4 bytes per row, not 512
    return o, _pack_lse(lse3, interpret=interpret)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------
def _col_from_packed(ref, i, block_q, scr):
    """Load this q-block's per-row stats from a COMPACT [s//128, 128] packed
    row into a [block_q, 1] VMEM column.  The slice-store loop is the
    relayout Mosaic can lower (a lanes->sublanes reshape is not); keeping
    lse/delta packed end-to-end means the backward never materializes the
    128x tile-padded [bh, s, 1] HBM tensors (the r5 ViT OOM came back via
    scheduler-hoisted unpack kernels)."""
    nch = block_q // 128
    chunk = ref[0, pl.ds(i * nch, nch)]            # [bq//128, 128]
    for t in range(nch):
        scr[t * 128:(t + 1) * 128, 0] = chunk[t]
    return scr[:]


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    causal, sm_scale, block_q, block_k, num_q_blocks,
                    rep_heads, offset, has_segments=False, dropout_rate=0.0,
                    hq=1, hkv=1, num_k_blocks=1):
    rest = list(rest)
    qseg_ref = kseg_ref = seed_ref = None
    if has_segments:
        qseg_ref, kseg_ref = rest.pop(0), rest.pop(0)
    if dropout_rate > 0.0:
        seed_ref = rest.pop(0)
    dk_ref, dv_ref, dk_scr, dv_scr, lse_scr, delta_scr = rest
    # grid (bh_kv, j, rr, i): rr walks the rep q-heads sharing this kv head
    # (GQA — dk/dv accumulate over them), i walks q blocks
    j = pl.program_id(1)  # k-block
    rr = pl.program_id(2)  # q-head within the kv group (reduction)
    i = pl.program_id(3)  # q-block (reduction)

    @pl.when((i == 0) & (rr == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1 + offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = _col_from_packed(lse_ref, i, block_q, lse_scr)    # [bq, 1]
        delta = _col_from_packed(delta_ref, i, block_q, delta_scr)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids + offset >= k_ids, s, NEG_INF)
        if has_segments:
            s = jnp.where(qseg_ref[0, :, 0][:, None]
                          == kseg_ref[0, :, 0][None, :], s, NEG_INF)
        p = jnp.exp(s - lse)                            # [bq, bk]
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # rebuild the forward's mask for THIS q-head block: the fwd grid
            # b was the global q-row index
            b = pl.program_id(0)
            qh = (b // hkv) * hq + (b % hkv) * rep_heads + rr
            m = _dropout_mask(seed_ref[0], qh, i, j, num_q_blocks,
                              num_k_blocks, (block_q, block_k),
                              dropout_rate)
            pd = p * m
            dp = dp * m
        else:
            pd = p
        # dv += (masked p)^T do
        dv_scr[:] += jax.lax.dot_general(
            pd, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # ds = p * (masked dp - delta) * scale  (delta = rowsum(do∘o) holds
        # with dropout too: o already contains the mask)
        ds = p * (dp - delta) * sm_scale
        # dk += ds^T q
        dk_scr[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((i == num_q_blocks - 1) & (rr == rep_heads - 1))
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   causal, sm_scale, block_q, block_k, num_k_blocks, offset,
                   has_segments=False, dropout_rate=0.0, num_q_blocks=1):
    rest = list(rest)
    qseg_ref = kseg_ref = seed_ref = None
    if has_segments:
        qseg_ref, kseg_ref = rest.pop(0), rest.pop(0)
    if dropout_rate > 0.0:
        seed_ref = rest.pop(0)
    dq_ref, dq_scr, lse_scr, delta_scr = rest
    j = pl.program_id(2)  # k-block (reduction)
    i = pl.program_id(1)  # q-block

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1 + offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = _col_from_packed(lse_ref, i, block_q, lse_scr)
        delta = _col_from_packed(delta_ref, i, block_q, delta_scr)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids + offset >= k_ids, s, NEG_INF)
        if has_segments:
            s = jnp.where(qseg_ref[0, :, 0][:, None]
                          == kseg_ref[0, :, 0][None, :], s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            b = pl.program_id(0)          # this grid's b IS the q-row index
            dp = dp * _dropout_mask(seed_ref[0], b, i, j, num_q_blocks,
                                    num_k_blocks, (block_q, block_k),
                                    dropout_rate)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_call(res, g, causal, sm_scale, interpret, n_q_heads=None,
              n_kv_heads=None, segment_ids=None, delta=None,
              dropout_rate=0.0, dropout_seed=None):
    q, k, v, o, lse = res
    do = g
    bh, s_q, d = q.shape
    bh_kv, s_k, _ = k.shape
    hq = n_q_heads or 1
    hkv = n_kv_heads or hq
    rep = hq // hkv
    block_q, block_k = _block_sizes(s_q, s_k, d)
    if delta is None:   # ring callers precompute it once across hops
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)                 # [bh, s_q] compact 2-D
    # lse/delta stay PACKED [bh, s//128, 128] end to end: the kernels read
    # full packed rows and relayout per q-block in VMEM (_col_from_packed)
    if lse.ndim == 3 and lse.shape[-1] == 1:
        lse = _pack_lse(lse, interpret)
    if delta.ndim == 3 and delta.shape[-1] == 1:
        delta = _pack_lse(delta, interpret)
    nch_q = s_q // 128
    lse_p = lse.reshape(bh, nch_q, 128)
    delta_p = delta.reshape(bh, nch_q, 128)
    has_seg = segment_ids is not None
    seed_arr = (jnp.asarray(dropout_seed, jnp.float32).reshape(1)
                if dropout_rate > 0.0 else None)

    def q_idx_dkv(b, j, rr, i):
        # b indexes B*Hkv; the q head is the rr-th member of its kv group
        return ((b // hkv) * hq + (b % hkv) * rep + rr, i, 0)

    def kv_idx_dkv(b, j, rr, i):
        return (b, j, 0)

    def stats_idx_dkv(b, j, rr, i):
        # full packed row of the rr-th q head in this kv group
        return ((b // hkv) * hq + (b % hkv) * rep + rr, 0, 0)

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), q_idx_dkv),
        pl.BlockSpec((1, block_k, d), kv_idx_dkv),
        pl.BlockSpec((1, block_k, d), kv_idx_dkv),
        pl.BlockSpec((1, block_q, d), q_idx_dkv),
        pl.BlockSpec((1, nch_q, 128), stats_idx_dkv),
        pl.BlockSpec((1, nch_q, 128), stats_idx_dkv),
    ]
    dkv_args = [q, k, v, do, lse_p, delta_p]
    if has_seg:
        seg3 = segment_ids[:, :, None]
        dkv_in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, rr, i: (b // hkv, i, 0)),
            pl.BlockSpec((1, block_k, 1),
                         lambda b, j, rr, i: (b // hkv, j, 0)),
        ]
        dkv_args += [seg3, seg3]
    if dropout_rate > 0.0:
        dkv_in_specs += [pl.BlockSpec(memory_space=pltpu.SMEM)]
        dkv_args += [seed_arr]

    dkv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k,
                          num_q_blocks=s_q // block_q, rep_heads=rep,
                          offset=s_k - s_q, has_segments=has_seg,
                          dropout_rate=dropout_rate, hq=hq, hkv=hkv,
                          num_k_blocks=s_k // block_k),
        grid=(bh_kv, s_k // block_k, rep, s_q // block_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), kv_idx_dkv),
            pl.BlockSpec((1, block_k, d), kv_idx_dkv),
        ],
        out_shape=[
            _sds((bh_kv, s_k, d), k.dtype, _vma_of(q, k, v, do)),
            _sds((bh_kv, s_k, d), v.dtype, _vma_of(q, k, v, do)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(*dkv_args)
    dk, dv = dkv

    def kv_idx_dq(b, i, j):
        return ((b // hq) * hkv + (b % hq) // rep, j, 0)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_idx_dq),
        pl.BlockSpec((1, block_k, d), kv_idx_dq),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, nch_q, 128), lambda b, i, j: (b, 0, 0)),
        pl.BlockSpec((1, nch_q, 128), lambda b, i, j: (b, 0, 0)),
    ]
    dq_args = [q, k, v, do, lse_p, delta_p]
    if has_seg:
        dq_in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b // hq, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b // hq, j, 0)),
        ]
        dq_args += [segment_ids[:, :, None], segment_ids[:, :, None]]
    if dropout_rate > 0.0:
        dq_in_specs += [pl.BlockSpec(memory_space=pltpu.SMEM)]
        dq_args += [seed_arr]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k,
                          num_k_blocks=s_k // block_k, offset=s_k - s_q,
                          has_segments=has_seg, dropout_rate=dropout_rate,
                          num_q_blocks=s_q // block_q),
        grid=(bh, s_q // block_q, s_k // block_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((bh, s_q, d), q.dtype, _vma_of(q, k, v, do)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op: [B, S, H, D] layout with custom VJP
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=16)
def _make_op(causal: bool, interpret: bool, has_segments: bool = False,
             dropout_rate: float = 0.0):
    """has_segments: op takes an extra arg seg [B, S] (f32 segment ids —
    intra-segment attention only, the varlen/flash_attn_unpadded mask;
    f32 so custom_vjp's cotangent contract stays uniform).

    dropout_rate > 0: op takes a trailing f32 scalar-array seed; the
    attention-probability dropout runs INSIDE the kernels (per-block
    regenerable PRNG — the S×S mask never exists in HBM), which is what
    keeps dropout-training configs (ERNIE/BERT pretrain) on the flash path
    instead of the materializing XLA fallback."""
    has_drop = dropout_rate > 0.0

    def _fwd(q, k, v, *rest):
        rest = list(rest)
        sids = rest.pop(0) if has_segments else None
        seed = rest.pop(0) if has_drop else None
        b, s_q, h, d = q.shape
        s_k = k.shape[1]
        hkv = k.shape[2]
        sm_scale = 1.0 / math.sqrt(d)
        qr = q.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
        kr = k.transpose(0, 2, 1, 3).reshape(b * hkv, s_k, d)
        vr = v.transpose(0, 2, 1, 3).reshape(b * hkv, s_k, d)
        o, lse = flash_attention_fwd_kernel_call(qr, kr, vr, causal, sm_scale,
                                                 interpret, n_q_heads=h,
                                                 n_kv_heads=hkv,
                                                 segment_ids=sids,
                                                 dropout_rate=dropout_rate,
                                                 dropout_seed=seed)
        o4 = o.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
        # name the bwd residuals so a save_only_these_names("fa_res") remat
        # policy keeps them and the backward skips re-running the fwd kernel
        from jax.ad_checkpoint import checkpoint_name
        res = tuple(checkpoint_name(x, "fa_res") for x in (qr, kr, vr, o, lse))
        return o4, res + (sids, seed, (b, h, hkv, s_q, s_k, d))

    n_extra = (1 if has_segments else 0) + (1 if has_drop else 0)
    if n_extra == 2:
        @jax.custom_vjp
        def op(q, k, v, seg, seed):
            return _fwd(q, k, v, seg, seed)[0]

        def fwd(q, k, v, seg, seed):
            return _fwd(q, k, v, seg, seed)
    elif n_extra == 1:
        @jax.custom_vjp
        def op(q, k, v, extra):
            return _fwd(q, k, v, extra)[0]

        def fwd(q, k, v, extra):
            return _fwd(q, k, v, extra)
    else:
        @jax.custom_vjp
        def op(q, k, v):
            return _fwd(q, k, v)[0]

        def fwd(q, k, v):
            return _fwd(q, k, v)

    def bwd(res, g):
        qr, kr, vr, o, lse, sids, seed, (b, h, hkv, s_q, s_k, d) = res
        sm_scale = 1.0 / math.sqrt(d)
        do = g.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
        dq, dk, dv = _bwd_call((qr, kr, vr, o, lse), do, causal, sm_scale,
                               interpret, n_q_heads=h, n_kv_heads=hkv,
                               segment_ids=sids, dropout_rate=dropout_rate,
                               dropout_seed=seed)
        dq4 = dq.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
        dk4 = dk.reshape(b, hkv, s_k, d).transpose(0, 2, 1, 3)
        dv4 = dv.reshape(b, hkv, s_k, d).transpose(0, 2, 1, 3)
        extras = ()
        if has_segments:
            extras += (jnp.zeros_like(sids),)
        if has_drop:
            extras += (jnp.zeros_like(seed),)
        return (dq4, dk4, dv4) + extras

    op.defvjp(fwd, bwd)
    return op


def _supported(q_shape, k_shape, causal=False):
    b, s_q, h, d = q_shape
    s_k = k_shape[1]
    hkv = k_shape[2]
    if h % hkv != 0:
        return False
    if d > 256 or d % 8 != 0:
        return False
    if causal and s_q > s_k:
        # bottom-right-aligned causal leaves rows [0, s_q - s_k) with zero
        # valid keys; their softmax is ill-defined and the XLA fallback's
        # uniform-weight convention differs from FA's zero-output — defer to
        # the fallback for this shape.
        return False
    for s in (s_q, s_k):
        if s % 128 != 0:
            return False
    return True


def _pad_to_tile(q, k, v, segment_ids):
    """Pad an untileable sequence length up to the next 128-multiple and
    mask the tail via the kernel's segment ids (padding gets a segment of
    its own, so real tokens never attend it).  This is what keeps e.g.
    ViT's S=197 attention on the flash path instead of the
    [B,H,S,S]-materializing XLA fallback (round-5 ViT profile: the
    materialized probs were both the memory AND the throughput ceiling)."""
    b, s, h, d = q.shape
    pad = (-s) % 128
    qp = jnp.pad(q, [(0, 0), (0, pad), (0, 0), (0, 0)])
    kp = jnp.pad(k, [(0, 0), (0, pad), (0, 0), (0, 0)])
    vp = jnp.pad(v, [(0, 0), (0, pad), (0, 0), (0, 0)])
    if segment_ids is None:
        seg = jnp.zeros((b, s), jnp.float32)
    else:
        seg = segment_ids.astype(jnp.float32)
    # the pad segment id must differ from every real id (real ids are
    # small non-negative ints in practice; -1 stays distinct)
    segp = jnp.pad(seg, [(0, 0), (0, pad)], constant_values=-1.0)
    return qp, kp, vp, segp, s


def flash_attention_ref(q, k, v, causal=False):
    """jnp reference with identical semantics to the kernel's core path
    ([B, S, H, D] layout, GQA via up-materialized K/V, fp32 softmax) — the
    parity tests' oracle and the off-TPU dispatch fallback.  Materializes
    the [B, H, S, S] score tensor; use the kernel for real workloads."""
    d = q.shape[-1]
    if k.shape[2] != q.shape[2]:  # GQA: up-materialize only in the fallback
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) \
        / math.sqrt(d)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def flash_attention(q, k, v, causal=False, interpret=False, segment_ids=None,
                    dropout_rate=0.0, dropout_seed=None):
    """[B, S, H, D] flash attention; falls back unsupported shapes to the
    caller (returns None so the dispatch default runs).

    segment_ids: optional int [B, S] — attention stays within equal-id
    spans (the varlen/flash_attn_unpadded mask; reference
    flash_attn_kernel.cu varlen entries). Requires s_q == s_k.

    dropout_rate/dropout_seed: in-kernel attention-probability dropout
    (per-block regenerable PRNG; the mask never exists in HBM).  seed may
    be a traced scalar — it does not bake into the executable.
    """
    drop = float(dropout_rate or 0.0)
    if drop >= 1.0:
        # torch/paddle semantics: dropout_p == 1 zeroes the output (the
        # kernel's uint32 threshold would wrap and emit inf instead).
        # Checked BEFORE pad-to-tile so the zeros match the caller's shape.
        return jnp.zeros_like(q)
    unpad_to = None
    if not _supported(q.shape, k.shape, causal):
        s_q, s_k = q.shape[1], k.shape[1]
        # pad-to-tile engages only for LONG untileable sequences: at short S
        # the padded kernel's small tiles starve the MXU and lose to XLA's
        # fused-softmax path (measured r5: ViT S=197->256 B=64, FA-pad 197
        # img/s vs XLA 243) while the memory it saves is modest; at S >= 384
        # the S^2 materialization cost dominates and FA wins
        tileable = (s_q == s_k and s_q % 128 != 0 and s_q >= 384
                    and _supported(q.shape[:1] + (128,) + q.shape[2:],
                                   k.shape[:1] + (128,) + k.shape[2:],
                                   causal))
        if not tileable:
            return None
        q, k, v, segment_ids, unpad_to = _pad_to_tile(q, k, v, segment_ids)
    extras = ()
    has_seg = segment_ids is not None
    if has_seg:
        if q.shape[1] != k.shape[1]:
            return None
        extras += (segment_ids.astype(jnp.float32),)
    if drop > 0.0:
        if dropout_seed is None:
            # fresh mask per call (the reference CUDA FA draws a philox seed
            # when none is fixed) — a constant default would repeat the
            # identical mask every step and layer
            from ...core.random import split_key
            dropout_seed = jax.random.randint(split_key(), (), 0, 1 << 23)
        extras += (jnp.asarray(dropout_seed, jnp.float32),)
    out = _make_op(bool(causal), bool(interpret), has_seg, drop)(
        q, k, v, *extras)
    if unpad_to is not None:
        out = out[:, :unpad_to]
    return out
