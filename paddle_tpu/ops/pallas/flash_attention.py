"""Pallas TPU flash attention (forward + backward).

The TPU-native replacement for the reference's CUDA FA2 kernel
(paddle/phi/kernels/gpu/flash_attn_kernel.cu + third_party flashattn):
online-softmax tiling so the S×S score matrix never hits HBM.

Layout: [B, S, H, D] at the API (reference flash_attention.py convention);
kernels run per (batch*head) over [BH, S, D] with q-block × k-block tiling.

Forward: FlashAttention-2 style — one pass over K/V blocks per Q block with a
running max/denominator in VMEM scratch; emits O and the per-row logsumexp L.
Backward: two kernels (dKdV accumulating over Q blocks; dQ accumulating over
K blocks) using the saved L and D = rowsum(dO ∘ O).

Grid iteration puts the reduction dim last ("arbitrary" semantics) so output
blocks are revisited with live scratch.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_fwd_kernel_call"]

NEG_INF = -1e30


def _block_sizes(s_q, s_k, d):
    """v5e-measured defaults (round-4 sweep on the 271M llama train step):
    k-blocks of 1024 beat 512 at every config (+6% at S=2048); q-blocks of
    512 win at S<=4k, 1024 at S>=8k (+5% at S=8192).  128-multiple
    fallbacks keep odd shapes tileable."""
    bq_pref = 1024 if s_q >= 8192 else 512
    bq = next((b for b in (bq_pref, 512, 256, 128) if s_q % b == 0 and b <= s_q),
              s_q)
    bk = next((b for b in (1024, 512, 256, 128) if s_k % b == 0 and b <= s_k),
              s_k)
    return bq, bk


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal, sm_scale, block_q,
                block_k, num_k_blocks, offset, has_segments=False):
    if has_segments:
        qseg_ref, kseg_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    j = pl.program_id(2)  # k-block index (innermost, reduction)
    i = pl.program_id(1)  # q-block index

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: process only blocks with k_start <= q_end
    run = True
    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1 + offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]                      # [block_q, d]
        k = k_ref[0]                      # [block_k, d]
        v = v_ref[0]                      # [block_k, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk]
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids + offset >= k_ids, s, NEG_INF)
        if has_segments:
            qs = qseg_ref[0, :, 0]        # [block_q] (f32 segment ids)
            ks = kseg_ref[0, :, 0]        # [block_k]
            s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
        m_prev = m_scr[:]                 # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)            # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)   # [bq, 1]
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:]
        inv = jnp.where(l > 0.0, 1.0 / jnp.where(l > 0.0, l, 1.0), 0.0)
        o_ref[0] = (acc_scr[:] * inv).astype(o_ref.dtype)
        lse_ref[0] = m_scr[:] + jnp.log(jnp.maximum(l, 1e-30))


def _vma_of(*arrs):
    """Union of manual-axes (shard_map vma) of the inputs: pallas_call
    out_shapes must declare it when the kernel runs inside shard_map."""
    out = frozenset()
    for a in arrs:
        out |= getattr(getattr(a, "aval", None), "vma", frozenset()) or frozenset()
    return out


def _sds(shape, dtype, vma):
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def flash_attention_fwd_kernel_call(q, k, v, causal, sm_scale, interpret=False,
                                    n_q_heads=None, n_kv_heads=None,
                                    segment_ids=None):
    """q: [B*Hq, S, D], k/v: [B*Hkv, S, D] -> (o [B*Hq, Sq, D], lse).

    GQA (n_kv_heads < n_q_heads) is handled in the BlockSpec index maps: the
    kernel reads KV blocks of head h // rep directly from HBM — no
    materialized jnp.repeat of K/V (reference flash_attn_kernel.cu GQA path).
    """
    bh, s_q, d = q.shape
    s_k = k.shape[1]
    hq = n_q_heads or 1
    hkv = n_kv_heads or hq
    rep = hq // hkv
    block_q, block_k = _block_sizes(s_q, s_k, d)
    grid = (bh, s_q // block_q, s_k // block_k)

    def kv_idx(b, i, j):
        return ((b // hq) * hkv + (b % hq) // rep, j, 0)

    has_seg = segment_ids is not None
    kernel = functools.partial(
        _fwd_kernel, causal=causal, sm_scale=sm_scale, block_q=block_q,
        block_k=block_k, num_k_blocks=s_k // block_k, offset=s_k - s_q,
        has_segments=has_seg)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_idx),
        pl.BlockSpec((1, block_k, d), kv_idx),
    ]
    args = [q, k, v]
    if has_seg:
        # segment ids per batch row [B, S] (f32), broadcast over heads
        seg3 = segment_ids[:, :, None]   # [B, S, 1]: TPU tiling wants
        in_specs += [                     # (8·k, full-last-dim) blocks
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b // hq, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b // hq, j, 0)),
        ]
        args += [seg3, seg3]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, s_q, d), q.dtype, _vma_of(q, k, v)),
            _sds((bh, s_q, 1), jnp.float32, _vma_of(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                    causal, sm_scale, block_q, block_k, num_q_blocks,
                    rep_heads, offset, has_segments=False):
    if has_segments:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    # grid (bh_kv, j, rr, i): rr walks the rep q-heads sharing this kv head
    # (GQA — dk/dv accumulate over them), i walks q blocks
    j = pl.program_id(1)  # k-block
    rr = pl.program_id(2)  # q-head within the kv group (reduction)
    i = pl.program_id(3)  # q-block (reduction)

    @pl.when((i == 0) & (rr == 0))
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = True
    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1 + offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                                # [bq, 1]
        delta = delta_ref[0]                            # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids + offset >= k_ids, s, NEG_INF)
        if has_segments:
            s = jnp.where(qseg_ref[0, :, 0][:, None]
                          == kseg_ref[0, :, 0][None, :], s, NEG_INF)
        p = jnp.exp(s - lse)                            # [bq, bk]
        # dv += p^T do
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        # dk += ds^T q
        dk_scr[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((i == num_q_blocks - 1) & (rr == rep_heads - 1))
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
                   causal, sm_scale, block_q, block_k, num_k_blocks, offset,
                   has_segments=False):
    if has_segments:
        qseg_ref, kseg_ref, dq_ref, dq_scr = rest
    else:
        dq_ref, dq_scr = rest
    j = pl.program_id(2)  # k-block (reduction)
    i = pl.program_id(1)  # q-block

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = (j * block_k) <= (i * block_q + block_q - 1 + offset)

    @pl.when(run)
    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids + offset >= k_ids, s, NEG_INF)
        if has_segments:
            s = jnp.where(qseg_ref[0, :, 0][:, None]
                          == kseg_ref[0, :, 0][None, :], s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_call(res, g, causal, sm_scale, interpret, n_q_heads=None,
              n_kv_heads=None, segment_ids=None, delta=None):
    q, k, v, o, lse = res
    do = g
    bh, s_q, d = q.shape
    bh_kv, s_k, _ = k.shape
    hq = n_q_heads or 1
    hkv = n_kv_heads or hq
    rep = hq // hkv
    block_q, block_k = _block_sizes(s_q, s_k, d)
    if delta is None:   # ring callers precompute it once across hops
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)  # [bh, s_q, 1]
    has_seg = segment_ids is not None

    def q_idx_dkv(b, j, rr, i):
        # b indexes B*Hkv; the q head is the rr-th member of its kv group
        return ((b // hkv) * hq + (b % hkv) * rep + rr, i, 0)

    def kv_idx_dkv(b, j, rr, i):
        return (b, j, 0)

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), q_idx_dkv),
        pl.BlockSpec((1, block_k, d), kv_idx_dkv),
        pl.BlockSpec((1, block_k, d), kv_idx_dkv),
        pl.BlockSpec((1, block_q, d), q_idx_dkv),
        pl.BlockSpec((1, block_q, 1), q_idx_dkv),
        pl.BlockSpec((1, block_q, 1), q_idx_dkv),
    ]
    dkv_args = [q, k, v, do, lse, delta]
    if has_seg:
        seg3 = segment_ids[:, :, None]
        dkv_in_specs += [
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, rr, i: (b // hkv, i, 0)),
            pl.BlockSpec((1, block_k, 1),
                         lambda b, j, rr, i: (b // hkv, j, 0)),
        ]
        dkv_args += [seg3, seg3]

    dkv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k,
                          num_q_blocks=s_q // block_q, rep_heads=rep,
                          offset=s_k - s_q, has_segments=has_seg),
        grid=(bh_kv, s_k // block_k, rep, s_q // block_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), kv_idx_dkv),
            pl.BlockSpec((1, block_k, d), kv_idx_dkv),
        ],
        out_shape=[
            _sds((bh_kv, s_k, d), k.dtype, _vma_of(q, k, v, do)),
            _sds((bh_kv, s_k, d), v.dtype, _vma_of(q, k, v, do)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(*dkv_args)
    dk, dv = dkv

    def kv_idx_dq(b, i, j):
        return ((b // hq) * hkv + (b % hq) // rep, j, 0)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_idx_dq),
        pl.BlockSpec((1, block_k, d), kv_idx_dq),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_args = [q, k, v, do, lse, delta]
    if has_seg:
        dq_in_specs += [
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b // hq, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i, j: (b // hq, j, 0)),
        ]
        dq_args += [segment_ids[:, :, None], segment_ids[:, :, None]]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, sm_scale=sm_scale,
                          block_q=block_q, block_k=block_k,
                          num_k_blocks=s_k // block_k, offset=s_k - s_q,
                          has_segments=has_seg),
        grid=(bh, s_q // block_q, s_k // block_k),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((bh, s_q, d), q.dtype, _vma_of(q, k, v, do)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public op: [B, S, H, D] layout with custom VJP
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=16)
def _make_op(causal: bool, interpret: bool, has_segments: bool = False):
    """has_segments: op takes a 4th arg seg [B, S] (f32 segment ids —
    intra-segment attention only, the varlen/flash_attn_unpadded mask;
    f32 so custom_vjp's cotangent contract stays uniform)."""

    def _fwd(q, k, v, *seg):
        b, s_q, h, d = q.shape
        s_k = k.shape[1]
        hkv = k.shape[2]
        sm_scale = 1.0 / math.sqrt(d)
        qr = q.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
        kr = k.transpose(0, 2, 1, 3).reshape(b * hkv, s_k, d)
        vr = v.transpose(0, 2, 1, 3).reshape(b * hkv, s_k, d)
        sids = seg[0] if seg else None
        o, lse = flash_attention_fwd_kernel_call(qr, kr, vr, causal, sm_scale,
                                                 interpret, n_q_heads=h,
                                                 n_kv_heads=hkv,
                                                 segment_ids=sids)
        o4 = o.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
        # name the bwd residuals so a save_only_these_names("fa_res") remat
        # policy keeps them and the backward skips re-running the fwd kernel
        from jax.ad_checkpoint import checkpoint_name
        res = tuple(checkpoint_name(x, "fa_res") for x in (qr, kr, vr, o, lse))
        return o4, res + (sids, (b, h, hkv, s_q, s_k, d))

    if has_segments:
        @jax.custom_vjp
        def op(q, k, v, seg):
            o, _ = _fwd(q, k, v, seg)
            return o

        def fwd(q, k, v, seg):
            return _fwd(q, k, v, seg)
    else:
        @jax.custom_vjp
        def op(q, k, v):
            o, _ = _fwd(q, k, v)
            return o

        def fwd(q, k, v):
            return _fwd(q, k, v)

    def bwd(res, g):
        qr, kr, vr, o, lse, sids, (b, h, hkv, s_q, s_k, d) = res
        sm_scale = 1.0 / math.sqrt(d)
        do = g.transpose(0, 2, 1, 3).reshape(b * h, s_q, d)
        dq, dk, dv = _bwd_call((qr, kr, vr, o, lse), do, causal, sm_scale,
                               interpret, n_q_heads=h, n_kv_heads=hkv,
                               segment_ids=sids)
        dq4 = dq.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)
        dk4 = dk.reshape(b, hkv, s_k, d).transpose(0, 2, 1, 3)
        dv4 = dv.reshape(b, hkv, s_k, d).transpose(0, 2, 1, 3)
        if has_segments:
            return dq4, dk4, dv4, jnp.zeros_like(sids)
        return dq4, dk4, dv4

    op.defvjp(fwd, bwd)
    return op


def _supported(q, k, causal=False):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    hkv = k.shape[2]
    if h % hkv != 0:
        return False
    if d > 256 or d % 8 != 0:
        return False
    if causal and s_q > s_k:
        # bottom-right-aligned causal leaves rows [0, s_q - s_k) with zero
        # valid keys; their softmax is ill-defined and the XLA fallback's
        # uniform-weight convention differs from FA's zero-output — defer to
        # the fallback for this shape.
        return False
    for s in (s_q, s_k):
        if s % 128 != 0 and s < 128:
            return False
        if s % 128 != 0:
            return False
    return True


def flash_attention(q, k, v, causal=False, interpret=False, segment_ids=None):
    """[B, S, H, D] flash attention; falls back unsupported shapes to the
    caller (returns None so the dispatch default runs).

    segment_ids: optional int [B, S] — attention stays within equal-id
    spans (the varlen/flash_attn_unpadded mask; reference
    flash_attn_kernel.cu varlen entries). Requires s_q == s_k.
    """
    if not _supported(q, k, causal):
        return None
    if segment_ids is not None:
        if q.shape[1] != k.shape[1]:
            return None
        sids = segment_ids.astype(jnp.float32)
        return _make_op(bool(causal), bool(interpret), True)(q, k, v, sids)
    return _make_op(bool(causal), bool(interpret))(q, k, v)
