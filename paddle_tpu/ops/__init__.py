from . import pallas  # noqa: F401
