"""Profiler (reference: python/paddle/profiler/ — Profiler profiler.py:358
with scheduler states, RecordEvent event_tracing.h, timer.py throughput).

TPU-native: device tracing delegates to jax.profiler (XPlane → TensorBoard /
perfetto, the CUPTI-chrome-trace analog); host annotations map RecordEvent →
jax.profiler.TraceAnnotation + named_scope so they appear in the same trace.
The benchmark `Timer` reproduces timer.py's ips accounting (used by bench.py).
"""
from __future__ import annotations

import contextlib
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "benchmark", "Timer", "SummaryView", "host_annotation"]


def host_annotation(name: str):
    """Context manager that lands a host span in the jax device timeline
    (``jax.profiler.TraceAnnotation``) when the backing jax build supports
    it, else a no-op — the bridge ``paddle_tpu.observability`` uses so
    engine-step phase spans appear NEXT TO the XLA ops they dispatched in
    one Perfetto view.  Safe to enter with no device trace active."""
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """reference scheduler_fn: maps step -> ProfilerState."""
    period = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


class RecordEvent:
    """Host annotation (reference phi/api/profiler/event_tracing.h RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """reference profiler.py:358."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0], closed=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._dir = "/tmp/paddle_tpu_profile"
        self._active = False
        self.timer = Timer()
        # eager per-op events collected via the dispatch hook:
        # (name, t_start_s, dur_s, out_shapes)
        self._op_events = []

    def _attach_op_timer(self):
        from ..core import dispatch as _dispatch
        _dispatch._op_timer[0] = self._op_events

    def _detach_op_timer(self):
        from ..core import dispatch as _dispatch
        if _dispatch._op_timer[0] is self._op_events:
            _dispatch._op_timer[0] = None

    def start(self):
        self.timer.begin()
        if self._timer_only:
            return
        state = self._scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            try:
                jax.profiler.start_trace(self._dir)
            except Exception:
                pass  # a second concurrent device trace is a host-only run
            self._active = True
            self._attach_op_timer()

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
            self._detach_op_timer()
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def step(self, num_samples=None):
        self.timer.step(num_samples)
        if self._timer_only:
            self._step += 1
            return
        prev = self._scheduler(self._step)
        self._step += 1
        cur = self._scheduler(self._step)
        if prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and \
                cur in (ProfilerState.CLOSED, ProfilerState.READY):
            if self._active:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._active = False
                self._detach_op_timer()
        elif cur in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and \
                not self._active:
            try:
                jax.profiler.start_trace(self._dir)
            except Exception:
                pass
            self._active = True
            self._attach_op_timer()

    def step_info(self, unit="samples"):
        return self.timer.step_info(unit)

    def _op_stats(self):
        """Aggregate eager op events -> {name: [count, total_s, min, max]}."""
        agg = {}
        for name, _t0, dur, _shapes in self._op_events:
            e = agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
            e[0] += 1
            e[1] += dur
            e[2] = min(e[2], dur)
            e[3] = max(e[3], dur)
        return agg

    def _device_op_stats(self):
        """Per-op SELF times from the newest jax XPlane chrome trace under
        self._dir (jit workloads: the eager hook sees only staged tracing,
        the device trace has the real kernel times). Returns the same
        aggregate mapping or {} when no trace exists."""
        import glob
        import gzip
        import json as _json
        import re
        files = sorted(glob.glob(
            f"{self._dir}/**/*.trace.json.gz", recursive=True))
        if not files:
            return {}
        try:
            with gzip.open(files[-1]) as f:
                data = _json.load(f)
        except Exception:
            return {}
        meta = {}
        for e in data.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "thread_name":
                meta[(e.get("pid"), e.get("tid"))] = e["args"].get("name")
        evs = [e for e in data.get("traceEvents", [])
               if e.get("ph") == "X"
               and meta.get((e.get("pid"), e.get("tid"))) == "XLA Ops"]
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        agg = {}
        stack = []
        for e in evs:
            ts, dur = e["ts"], e["dur"]
            name = re.sub(r"[.\d]+$", "", e["name"])
            while stack and stack[-1][1] <= ts:
                stack.pop()
            if stack:
                agg[stack[-1][2]][1] -= dur / 1e6
            en = agg.setdefault(name, [0, 0.0, float("inf"), 0.0])
            en[0] += 1
            en[1] += dur / 1e6
            en[2] = min(en[2], dur / 1e6)
            en[3] = max(en[3], dur / 1e6)
            stack.append((ts, ts + dur, name))
        return agg

    @staticmethod
    def _format_table(title, agg, unit_div):
        total = sum(e[1] for e in agg.values()) or 1e-12
        lines = [title,
                 f"{'Name':<40}{'Calls':>8}{'Total':>12}{'Avg':>12}"
                 f"{'Min':>12}{'Max':>12}{'Ratio %':>9}"]
        for name, (cnt, tot, mn, mx) in sorted(
                agg.items(), key=lambda kv: -kv[1][1]):
            lines.append(
                f"{name[:39]:<40}{cnt:>8}"
                f"{tot / unit_div:>12.4f}{tot / cnt / unit_div:>12.4f}"
                f"{mn / unit_div:>12.4f}{mx / unit_div:>12.4f}"
                f"{100 * tot / total:>8.1f}%")
        return "\n".join(lines)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        """Operator / kernel statistics tables (reference
        profiler_statistic.py:1 OperatorView + DeviceView): eager per-op
        host times from the dispatch hook, plus device-kernel self-times
        parsed from the jax trace when one was captured."""
        div = {"s": 1.0, "ms": 1e-3, "us": 1e-6}.get(time_unit, 1e-3)
        parts = []
        op_agg = self._op_stats()
        if op_agg:
            parts.append(self._format_table(
                f"-- Operator Summary (host, {time_unit}) --", op_agg, div))
        dev_agg = self._device_op_stats()
        if dev_agg:
            parts.append(self._format_table(
                f"-- Device Kernel Summary (self time, {time_unit}) --",
                dev_agg, div))
        parts.append(f"-- Benchmark: {self.timer.step_info()} --")
        if not op_agg and not dev_agg:
            parts.append("(no events recorded; XPlane trace dir: "
                         + self._dir + ")")
        return "\n\n".join(parts)

    def export(self, path, format="json"):
        """Write the collected events as a chrome://tracing-loadable JSON
        (reference chrometracing_logger.cc)."""
        import json as _json
        if format != "json":
            raise ValueError(f"unsupported export format {format!r}")
        events = [{"name": "process_name", "ph": "M", "pid": 0,
                   "args": {"name": "paddle_tpu eager ops"}}]
        for name, t0, dur, shapes in self._op_events:
            events.append({
                "name": name, "ph": "X", "cat": "operator",
                "pid": 0, "tid": 0,
                "ts": round(t0 * 1e6, 3), "dur": round(dur * 1e6, 3),
                "args": {"output_shapes": [list(s) for s in shapes]},
            })
        with open(path, "w") as f:
            _json.dump({"traceEvents": events,
                        "displayTimeUnit": "ms"}, f)
        return path

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._dir = dir_name
    return handler


def load_profiler_result(path):
    return None


class Timer:
    """Throughput benchmark (reference python/paddle/profiler/timer.py):
    tracks step latency + ips with warmup skipping."""

    def __init__(self, skip_steps=10):
        self.skip = skip_steps
        self.reset()

    def reset(self):
        self._count = 0
        self._total_time = 0.0
        self._total_samples = 0
        self._last = None
        self._step_time = 0.0

    def begin(self):
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self._step_time = dt
            self._count += 1
            if self._count > self.skip:
                self._total_time += dt
                if num_samples:
                    self._total_samples += num_samples
        self._last = now

    @property
    def ips(self):
        if self._total_time <= 0:
            return 0.0
        n = self._count - self.skip
        if self._total_samples:
            return self._total_samples / self._total_time
        return n / self._total_time

    @property
    def avg_step_time(self):
        n = max(self._count - self.skip, 1)
        return self._total_time / n if self._total_time else self._step_time

    def step_info(self, unit="samples"):
        return (f"avg_step_time: {self.avg_step_time * 1000:.2f} ms, "
                f"ips: {self.ips:.2f} {unit}/s")


class benchmark:
    """`paddle.profiler.benchmark()` style helper."""

    def __init__(self):
        self.timer = Timer()

    def begin(self):
        self.timer.begin()

    def step(self, num_samples=None):
        self.timer.step(num_samples)

    def end(self):
        pass

    def step_info(self, unit="samples"):
        return self.timer.step_info(unit)
