"""Profiler (reference: python/paddle/profiler/ — Profiler profiler.py:358
with scheduler states, RecordEvent event_tracing.h, timer.py throughput).

TPU-native: device tracing delegates to jax.profiler (XPlane → TensorBoard /
perfetto, the CUPTI-chrome-trace analog); host annotations map RecordEvent →
jax.profiler.TraceAnnotation + named_scope so they appear in the same trace.
The benchmark `Timer` reproduces timer.py's ips accounting (used by bench.py).
"""
from __future__ import annotations

import contextlib
import time
from enum import Enum
from typing import Callable, Iterable, Optional

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "benchmark", "Timer", "SummaryView"]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """reference scheduler_fn: maps step -> ProfilerState."""
    period = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


class RecordEvent:
    """Host annotation (reference phi/api/profiler/event_tracing.h RecordEvent)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    """reference profiler.py:358."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0], closed=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._dir = "/tmp/paddle_tpu_profile"
        self._active = False
        self.timer = Timer()

    def start(self):
        self.timer.begin()
        if self._timer_only:
            return
        state = self._scheduler(self._step)
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            jax.profiler.start_trace(self._dir)
            self._active = True

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def step(self, num_samples=None):
        self.timer.step(num_samples)
        if self._timer_only:
            self._step += 1
            return
        prev = self._scheduler(self._step)
        self._step += 1
        cur = self._scheduler(self._step)
        if prev in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and \
                cur in (ProfilerState.CLOSED, ProfilerState.READY):
            if self._active:
                jax.profiler.stop_trace()
                self._active = False
        elif cur in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) and \
                not self._active:
            jax.profiler.start_trace(self._dir)
            self._active = True

    def step_info(self, unit="samples"):
        return self.timer.step_info(unit)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        return "profiler summary: see TensorBoard XPlane trace at " + self._dir

    def export(self, path, format="json"):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._dir = dir_name
    return handler


def load_profiler_result(path):
    return None


class Timer:
    """Throughput benchmark (reference python/paddle/profiler/timer.py):
    tracks step latency + ips with warmup skipping."""

    def __init__(self, skip_steps=10):
        self.skip = skip_steps
        self.reset()

    def reset(self):
        self._count = 0
        self._total_time = 0.0
        self._total_samples = 0
        self._last = None
        self._step_time = 0.0

    def begin(self):
        self._last = time.perf_counter()

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            dt = now - self._last
            self._step_time = dt
            self._count += 1
            if self._count > self.skip:
                self._total_time += dt
                if num_samples:
                    self._total_samples += num_samples
        self._last = now

    @property
    def ips(self):
        if self._total_time <= 0:
            return 0.0
        n = self._count - self.skip
        if self._total_samples:
            return self._total_samples / self._total_time
        return n / self._total_time

    @property
    def avg_step_time(self):
        n = max(self._count - self.skip, 1)
        return self._total_time / n if self._total_time else self._step_time

    def step_info(self, unit="samples"):
        return (f"avg_step_time: {self.avg_step_time * 1000:.2f} ms, "
                f"ips: {self.ips:.2f} {unit}/s")


class benchmark:
    """`paddle.profiler.benchmark()` style helper."""

    def __init__(self):
        self.timer = Timer()

    def begin(self):
        self.timer.begin()

    def step(self, num_samples=None):
        self.timer.step(num_samples)

    def end(self):
        pass

    def step_info(self, unit="samples"):
        return self.timer.step_info(unit)
