"""Windowed health sentinel: typed alert rules with hysteresis + cooldown
(ISSUE 13 tentpole, part b).

The measurement plane (PRs 6-7, 12) records everything and interprets
nothing: there is no component that watches the registry series and says
"queue depth has been growing for thirty seconds" or "the TTFT error
budget is burning 4x too fast".  This module is that component —
deliberately boring, deterministic machinery:

  * :class:`AlertRule` — a named detector over a scalar reading
    (``sample_fn(ctx)``), with a breach threshold + direction, a
    persistence window (``fire_frac`` of the in-window readings must
    breach before firing — one spiky sample is not an incident), a CLEAR
    threshold for hysteresis (the whole window must sit back under it
    before the alert clears), and a post-clear ``cooldown_s`` before the
    rule may re-fire.  Derived rules reshape the reading:
    :class:`TrendRule` (windowed growth: newest - oldest),
    :class:`DeltaRule` (windowed delta of a cumulative counter, self-
    arming on the first zero delta so warm-up activity never pages),
    :class:`RatioDeltaRule` (windowed Δnum/Δden over two cumulative
    counters), :class:`BurnRateRule` (fast/slow dual-window SLO burn over
    the request summaries, via the shared
    :func:`~paddle_tpu.observability.slo.windowed_burn` math).
  * :class:`HealthSentinel` — evaluates the rules at engine-step ends
    (it rides the existing ``Telemetry.step_done`` -> ``sample_memory``
    hook: telemetry-off engines never construct it, zero new jits, zero
    per-token work).  Every timestamp comes from the injected telemetry
    clock, so seeded traffic scenarios drive the detectors
    deterministically (tests/test_health.py).  Fired/cleared alerts land
    in the flight recorder stamped with the active fault-plan context,
    fires auto-dump the ring (the postmortem shows the ramp that tripped
    the rule), and the live exporter serves ``report()`` at ``/alerts``
    with ``/healthz`` turning degraded-aware.

Default rule set (:func:`default_rules`): sustained queue growth,
pool-occupancy pressure, prefix-hit-rate collapse, TTFT SLO burn rate
(fast/slow dual window), ``frontend.ttft_pred_err_s`` drift, and
steady-state recompile events.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field

from .slo import windowed_burn

__all__ = ["Alert", "AlertRule", "TrendRule", "DeltaRule", "RatioDeltaRule",
           "BurnRateRule", "HealthSentinel", "default_rules",
           "autoscale_rules", "aggregate_alerts"]


@dataclass
class Alert:
    """One fired detector: the typed record the flight recorder, the
    ``/alerts`` endpoint, and the artifact sections all carry."""
    rule: str
    severity: str
    value: float
    threshold: float
    fired_at: float
    state: str = "firing"              # firing | cleared
    cleared_at: float | None = None
    context: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "severity": self.severity,
            "state": self.state, "value": round(self.value, 6),
            "threshold": self.threshold,
            "fired_at": round(self.fired_at, 6),
            "cleared_at": None if self.cleared_at is None
            else round(self.cleared_at, 6),
            "context": dict(self.context),
        }


class AlertRule:
    """A windowed threshold detector with hysteresis and cooldown.

    ``sample_fn(ctx)`` returns the instantaneous reading (None = nothing
    to observe this round; the window keeps its old samples).  ``ctx`` is
    the evaluating :class:`HealthSentinel` (``ctx.telemetry``,
    ``ctx.registries``, ``ctx.now``).

    Firing: over the readings inside ``window_s``, at least
    ``min_samples`` present and ``fire_frac`` of them breaching (reading
    ``>= threshold`` for ``direction="above"``, ``<=`` for ``"below"``),
    and the rule not inside its post-clear cooldown.  Clearing: every
    in-window reading back on the OK side of ``clear_threshold`` (default
    = ``threshold``; set it wider for hysteresis).  ``arm_above`` /
    ``arm_below`` keep the rule dormant until a reading has crossed that
    bound once — a hit-rate-collapse rule must not page an engine whose
    cache never warmed up in the first place."""

    def __init__(self, name: str, *, threshold: float,
                 sample_fn=None, severity: str = "warn",
                 direction: str = "above", clear_threshold: float | None = None,
                 window_s: float = 10.0, min_samples: int = 3,
                 fire_frac: float = 1.0, cooldown_s: float = 30.0,
                 arm_above: float | None = None,
                 arm_below: float | None = None,
                 description: str = ""):
        if direction not in ("above", "below"):
            raise ValueError(f"direction must be above|below, "
                             f"not {direction!r}")
        self.name = name
        self.threshold = float(threshold)
        self.sample_fn = sample_fn
        self.severity = severity
        self.direction = direction
        self.clear_threshold = self.threshold if clear_threshold is None \
            else float(clear_threshold)
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self.fire_frac = float(fire_frac)
        self.cooldown_s = float(cooldown_s)
        self.arm_above = arm_above
        self.arm_below = arm_below
        self.description = description

    # -- the reading -------------------------------------------------------
    def sample(self, ctx) -> float | None:
        return self.sample_fn(ctx) if self.sample_fn is not None else None

    def reset(self):
        """Window boundary: derived rules drop their internal baselines
        (the base rule keeps no state outside the sentinel)."""

    # -- predicates --------------------------------------------------------
    def breach(self, v: float) -> bool:
        return v >= self.threshold if self.direction == "above" \
            else v <= self.threshold

    def clear_ok(self, v: float) -> bool:
        return v < self.clear_threshold if self.direction == "above" \
            else v > self.clear_threshold

    def describe(self) -> dict:
        return {
            "threshold": self.threshold,
            "clear_threshold": self.clear_threshold,
            "direction": self.direction,
            "window_s": self.window_s,
            "min_samples": self.min_samples,
            "fire_frac": self.fire_frac,
            "cooldown_s": self.cooldown_s,
            "severity": self.severity,
            "description": self.description,
        }


class TrendRule(AlertRule):
    """Windowed GROWTH detector: the reading is ``newest - oldest`` over
    the raw samples inside ``window_s`` (None until two raw samples).
    ``min_value`` additionally requires the newest raw value itself to be
    at least that high — a queue "growing" 0 -> 3 is not pressure."""

    def __init__(self, name: str, *, raw_fn, min_value: float = 0.0,
                 **kw):
        super().__init__(name, **kw)
        self.raw_fn = raw_fn
        self.min_value = float(min_value)
        self._raw: deque = deque()

    def reset(self):
        self._raw.clear()

    def sample(self, ctx) -> float | None:
        v = self.raw_fn(ctx)
        if v is None:
            return None
        now = ctx.now
        self._raw.append((now, float(v)))
        while self._raw and self._raw[0][0] < now - self.window_s:
            self._raw.popleft()
        if len(self._raw) < 2:
            return None
        growth = self._raw[-1][1] - self._raw[0][1]
        if self._raw[-1][1] < self.min_value:
            # below the floor: report a non-breaching reading so the
            # window drains toward clear instead of holding stale growth
            return min(growth, 0.0) if self.direction == "above" \
                else max(growth, 0.0)
        return growth


class DeltaRule(AlertRule):
    """Windowed delta of a cumulative counter (``counter_fn(ctx)``), SELF-
    ARMING: readings are withheld (None) until one evaluation observes a
    ZERO delta — i.e. the counter went quiet once.  Warm-up activity
    (compiles, first-touch growth) therefore never fires; a fresh delta
    AFTER the quiet point is exactly the steady-state event the rule
    exists for (recompile creep is the silent p99 killer, PERF.md §12)."""

    def __init__(self, name: str, *, counter_fn, **kw):
        kw.setdefault("min_samples", 1)
        super().__init__(name, **kw)
        self.counter_fn = counter_fn
        self._last: float | None = None
        self._armed = False

    def reset(self):
        self._last = None
        self._armed = False

    def sample(self, ctx) -> float | None:
        v = self.counter_fn(ctx)
        if v is None:
            return None
        v = float(v)
        if self._last is None:
            self._last = v
            return None
        delta, self._last = v - self._last, v
        if not self._armed:
            if delta == 0.0:
                self._armed = True
            return None
        return delta


class RatioDeltaRule(AlertRule):
    """Windowed ratio of two cumulative counters: Δnum / Δden over the
    samples inside ``window_s`` (None while Δden < ``min_den`` — a rate
    over nothing is noise, not a reading).  The hit-rate-collapse and
    prediction-error-drift rules are both this shape."""

    def __init__(self, name: str, *, num_fn, den_fn, min_den: float = 1.0,
                 **kw):
        super().__init__(name, **kw)
        self.num_fn = num_fn
        self.den_fn = den_fn
        self.min_den = float(min_den)
        self._ring: deque = deque()

    def reset(self):
        self._ring.clear()

    def sample(self, ctx) -> float | None:
        num = self.num_fn(ctx)
        den = self.den_fn(ctx)
        if num is None or den is None:
            return None
        now = ctx.now
        self._ring.append((now, float(num), float(den)))
        while self._ring and self._ring[0][0] < now - self.window_s:
            self._ring.popleft()
        if len(self._ring) < 2:
            return None
        d_num = self._ring[-1][1] - self._ring[0][1]
        d_den = self._ring[-1][2] - self._ring[0][2]
        if d_den < self.min_den:
            return None
        return d_num / d_den


class BurnRateRule(AlertRule):
    """TTFT SLO burn rate, fast/slow DUAL window (the SRE pattern: the
    fast window catches a cliff quickly, the slow window keeps a brief
    blip from paging — fire only when BOTH burn above the threshold, so
    the reading is ``min(fast_burn, slow_burn)``).  Burn math is the
    shared :func:`~paddle_tpu.observability.slo.windowed_burn` over
    ``Telemetry.request_summaries`` (each stamped ``at`` retirement
    time); no duplicated goodput arithmetic."""

    def __init__(self, name: str, *, slo_ttft_s: float,
                 slo_target: float = 0.95, fast_window_s: float = 5.0,
                 slow_window_s: float = 30.0, min_requests: int = 4,
                 **kw):
        kw.setdefault("threshold", 1.0)
        kw.setdefault("window_s", fast_window_s)
        super().__init__(name, **kw)
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_target = float(slo_target)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.min_requests = int(min_requests)

    def sample(self, ctx) -> float | None:
        tel = ctx.telemetry
        if tel is None:
            return None
        summaries = tel.request_summaries
        fast = windowed_burn(summaries, self.slo_ttft_s,
                             slo_target=self.slo_target,
                             window_s=self.fast_window_s, now=ctx.now)
        slow = windowed_burn(summaries, self.slo_ttft_s,
                             slo_target=self.slo_target,
                             window_s=self.slow_window_s, now=ctx.now)
        if fast["requests"] < self.min_requests \
                or slow["requests"] < self.min_requests:
            return None
        return min(fast["burn_rate"], slow["burn_rate"])

    def describe(self) -> dict:
        d = super().describe()
        d.update(slo_ttft_s=self.slo_ttft_s, slo_target=self.slo_target,
                 fast_window_s=self.fast_window_s,
                 slow_window_s=self.slow_window_s,
                 min_requests=self.min_requests)
        return d


# ---------------------------------------------------------------------------
# default rule set
# ---------------------------------------------------------------------------
def _mem_last(ctx, field_name):
    tel = ctx.telemetry
    if tel is None:
        return None
    row = tel.memory.last
    return None if row is None else row.get(field_name)


def _frontend_hist(ctx, name):
    reg = ctx.registries.get("frontend")
    if reg is None or name not in reg:
        return None
    return reg.histogram(name)


def default_rules(*, slo_ttft_s: float | None = None,
                  slo_target: float = 0.95,
                  queue_growth: float = 8.0, queue_min_depth: float = 4.0,
                  queue_window_s: float = 5.0,
                  occupancy_threshold: float = 0.92,
                  occupancy_clear: float = 0.85,
                  occupancy_window_s: float = 5.0,
                  hit_rate_floor: float = 0.15, hit_rate_arm: float = 0.35,
                  hit_rate_window_s: float = 10.0,
                  pred_err_s: float | None = None,
                  burn_threshold: float = 1.0,
                  fast_window_s: float = 5.0, slow_window_s: float = 30.0,
                  cooldown_s: float = 30.0) -> list:
    """The stock sentinel: sustained queue growth, pool-occupancy
    pressure, prefix-hit-rate collapse, TTFT burn rate (only when a
    deadline is supplied), prediction-error drift (only when a bound is
    supplied — it needs the frontend registry attached), and steady-state
    recompiles.  Every threshold is a keyword so tests and deployments
    tune without subclassing."""
    rules: list = [
        TrendRule(
            "queue_growth",
            raw_fn=lambda ctx: _mem_last(ctx, "queue_depth"),
            threshold=queue_growth, min_value=queue_min_depth,
            window_s=queue_window_s, min_samples=3, fire_frac=0.6,
            clear_threshold=0.0, cooldown_s=cooldown_s,
            description="admission queue grew by >= threshold over the "
                        "window and is above the min depth — the "
                        "autoscaler trigger (ROADMAP item 5)"),
        AlertRule(
            "pool_pressure",
            sample_fn=lambda ctx: _mem_last(ctx, "occupancy_frac"),
            threshold=occupancy_threshold, clear_threshold=occupancy_clear,
            window_s=occupancy_window_s, min_samples=3, fire_frac=1.0,
            cooldown_s=cooldown_s,
            description="PagePool occupancy sustained above threshold — "
                        "the degradation ladder (evict/preempt) is near"),
        RatioDeltaRule(
            "prefix_hit_collapse",
            num_fn=lambda ctx: _mem_last(ctx, "cache_hit_tokens"),
            den_fn=lambda ctx: (
                None if _mem_last(ctx, "cache_hit_tokens") is None
                or _mem_last(ctx, "prefill_tokens_executed") is None
                else _mem_last(ctx, "cache_hit_tokens")
                + _mem_last(ctx, "prefill_tokens_executed")),
            min_den=32.0, threshold=hit_rate_floor, direction="below",
            arm_above=hit_rate_arm, window_s=hit_rate_window_s,
            min_samples=3, fire_frac=1.0, cooldown_s=cooldown_s,
            description="windowed prefix-cache hit rate collapsed below "
                        "the floor after having been warm — routing or "
                        "eviction regression"),
        DeltaRule(
            "recompile",
            counter_fn=lambda ctx: None if ctx.telemetry is None
            else ctx.telemetry._c_compiles.value,
            threshold=1.0, window_s=fast_window_s, fire_frac=0.01,
            min_samples=1, cooldown_s=cooldown_s,
            description="steady-state jit compile-cache miss (self-armed "
                        "after the first quiet evaluation) — recompile "
                        "creep is the silent p99 killer (PERF.md §12)"),
    ]
    if slo_ttft_s is not None:
        rules.append(BurnRateRule(
            "ttft_slo_burn", slo_ttft_s=slo_ttft_s, slo_target=slo_target,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            threshold=burn_threshold, min_samples=2, fire_frac=1.0,
            cooldown_s=cooldown_s, severity="page",
            description="TTFT error budget burning faster than allotted "
                        "over BOTH the fast and slow windows"))
    if pred_err_s is not None:
        rules.append(RatioDeltaRule(
            "ttft_pred_err_drift",
            num_fn=lambda ctx: (
                None if _frontend_hist(ctx, "frontend.ttft_pred_err_s")
                is None
                else _frontend_hist(ctx, "frontend.ttft_pred_err_s").total),
            den_fn=lambda ctx: (
                None if _frontend_hist(ctx, "frontend.ttft_pred_err_s")
                is None
                else float(_frontend_hist(
                    ctx, "frontend.ttft_pred_err_s").count)),
            min_den=4.0, threshold=pred_err_s,
            window_s=slow_window_s, min_samples=2, fire_frac=1.0,
            cooldown_s=cooldown_s,
            description="windowed mean admission-prediction error drifted "
                        "above the bound — the controller's model of the "
                        "engine has rotted"))
    return rules


def autoscale_rules(*, depth_fn, load_fn,
                    queue_growth: float = 4.0,
                    queue_min_depth: float = 3.0,
                    growth_window_s: float = 6.0,
                    growth_fire_frac: float = 0.5,
                    idle_per_replica: float = 0.5,
                    idle_window_s: float = 10.0,
                    min_samples: int = 3,
                    cooldown_s: float = 0.0) -> list:
    """The elastic-fleet autoscaler's rule pair (ROADMAP item 5): the
    same :func:`default_rules` ``queue_growth`` TrendRule shape — here
    over the FLEET-wide queue pressure ``depth_fn(ctx)`` — as the
    scale-UP trigger, plus ``fleet_idle`` (windowed per-routable-replica
    load ``load_fn(ctx)`` sustained below ``idle_per_replica``) as the
    scale-DOWN trigger.  Both run inside an ordinary
    :class:`HealthSentinel` under its injectable clock, so seeded
    traffic drives scaling decisions deterministically
    (serving/autoscale.py wires a round-based virtual clock by
    default)."""
    return [
        TrendRule(
            "queue_growth",
            raw_fn=depth_fn,
            threshold=queue_growth, min_value=queue_min_depth,
            window_s=growth_window_s, min_samples=min_samples,
            fire_frac=growth_fire_frac,
            # clear once the whole window stops growing (readings < 1) —
            # a drained-flat queue reads growth 0.0, which must clear the
            # alert, not hold it active into the next trough
            clear_threshold=1.0,
            cooldown_s=cooldown_s,
            description="fleet-wide admission-queue pressure grew by >= "
                        "threshold over the window and sits above the "
                        "min depth — the elastic scale-up trigger"),
        AlertRule(
            "fleet_idle",
            sample_fn=load_fn,
            threshold=idle_per_replica, direction="below",
            window_s=idle_window_s, min_samples=min_samples,
            fire_frac=1.0, cooldown_s=cooldown_s,
            description="per-routable-replica load sustained below the "
                        "idle floor for the whole window — the elastic "
                        "scale-down (drain) trigger"),
    ]


# ---------------------------------------------------------------------------
# the sentinel
# ---------------------------------------------------------------------------
class _RuleState:
    __slots__ = ("readings", "active", "last_cleared_at", "fires", "armed")

    def __init__(self):
        self.readings: deque = deque()      # (t, value)
        self.active: Alert | None = None
        self.last_cleared_at = -float("inf")
        self.fires = 0
        self.armed = False                  # arm_above/arm_below crossed


class HealthSentinel:
    """Evaluate a rule set over live telemetry at engine-step ends.

    Wire-up: ``Telemetry(sentinel=HealthSentinel(...))`` (or
    ``telemetry.attach_sentinel(sent)``) — ``Telemetry.step_done`` calls
    :meth:`on_step` right after the memory-observatory sample, so the
    sentinel sees each fresh series row with zero additional hooks.
    ``every_steps`` throttles evaluation; the clock is adopted from the
    telemetry (one injected fake clock drives sampling, windowing,
    cooldowns, and every Alert timestamp).

    ``rule_kw`` (anything :func:`default_rules` accepts, e.g.
    ``slo_ttft_s=0.5``) builds the stock rule set when ``rules`` is not
    given."""

    def __init__(self, rules=None, *, clock=None, every_steps: int = 1,
                 history: int = 64, **rule_kw):
        self.rules = list(rules) if rules is not None \
            else default_rules(**rule_kw)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {names}")
        self.clock = clock or time.perf_counter
        self.every_steps = max(1, int(every_steps))
        self._states = {r.name: _RuleState() for r in self.rules}
        self.history: deque = deque(maxlen=int(history))
        self.fired_total = 0
        self.evaluations = 0
        self._step_count = 0
        # evaluation context (rules read these)
        self.telemetry = None
        self.registries: dict = {}
        self.now = 0.0

    # -- wiring ------------------------------------------------------------
    def attach(self, telemetry) -> "HealthSentinel":
        """Adopt the telemetry's clock (one clock domain) and make it the
        default evaluation subject."""
        self.telemetry = telemetry
        self.clock = telemetry.clock
        return self

    def attach_registry(self, label: str, registry):
        """Expose an extra registry (e.g. the frontend admission
        controller's) to rules that read it."""
        self.registries[label] = registry

    def add_rule(self, rule: AlertRule) -> "HealthSentinel":
        """Add a rule after construction (e.g. a BurnRateRule once the
        deployment's SLO deadline has been calibrated)."""
        if rule.name in self._states:
            raise ValueError(f"rule {rule.name!r} already registered")
        self.rules.append(rule)
        self._states[rule.name] = _RuleState()
        return self

    def reset(self):
        """Measurement-window boundary (``Telemetry.reset_window`` calls
        this): drop readings and derived-rule baselines, force-clear any
        active alert WITHOUT a cleared event (the window that fired it is
        gone), keep the lifetime fire counters and history."""
        for r in self.rules:
            r.reset()
        for st in self._states.values():
            st.readings.clear()
            if st.active is not None:
                st.active.state = "cleared"
                st.active = None
            st.last_cleared_at = -float("inf")
            st.armed = False

    # -- evaluation --------------------------------------------------------
    def on_step(self, telemetry):
        """The step-end hook (rides sample_memory): throttled by
        ``every_steps``."""
        self._step_count += 1
        if self._step_count % self.every_steps == 0:
            self.evaluate(telemetry)

    def evaluate(self, telemetry=None, now: float | None = None) -> list:
        """One evaluation round over every rule; returns newly FIRED
        alerts.  Deterministic: same clock + same readings -> same fires."""
        tel = telemetry if telemetry is not None else self.telemetry
        self.telemetry = tel
        self.now = float(self.clock() if now is None else now)
        self.evaluations += 1
        fired: list[Alert] = []
        for rule in self.rules:
            st = self._states[rule.name]
            v = rule.sample(self)
            if v is not None:
                v = float(v)
                if rule.arm_above is not None or rule.arm_below is not None:
                    if not st.armed:
                        if (rule.arm_above is not None
                                and v >= rule.arm_above) or \
                                (rule.arm_below is not None
                                 and v <= rule.arm_below):
                            st.armed = True
                        else:
                            v = None
                if v is not None:
                    st.readings.append((self.now, v))
            while st.readings and st.readings[0][0] < self.now - rule.window_s:
                st.readings.popleft()
            n = len(st.readings)
            if st.active is None:
                if n < rule.min_samples:
                    continue
                breaches = sum(1 for _t, x in st.readings if rule.breach(x))
                if breaches / n >= rule.fire_frac \
                        and breaches >= 1 \
                        and self.now >= st.last_cleared_at + rule.cooldown_s:
                    last = st.readings[-1][1]
                    alert = Alert(rule=rule.name, severity=rule.severity,
                                  value=last, threshold=rule.threshold,
                                  fired_at=self.now,
                                  context={"window_samples": n,
                                           "breaches": breaches})
                    st.active = alert
                    st.fires += 1
                    self.fired_total += 1
                    self.history.append(alert)
                    fired.append(alert)
                    self._record_fire(alert)
            else:
                # hysteresis: the WHOLE window must read OK vs the clear
                # threshold (and be populated) before the alert clears
                if n >= rule.min_samples and \
                        all(rule.clear_ok(x) for _t, x in st.readings):
                    st.active.state = "cleared"
                    st.active.cleared_at = self.now
                    st.last_cleared_at = self.now
                    self._record_clear(st.active)
                    st.active = None
                else:
                    st.active.value = st.readings[-1][1] if n \
                        else st.active.value
        return fired

    def _record_fire(self, alert: Alert):
        tel = self.telemetry
        if tel is None:
            return
        hook = getattr(tel, "alert_fired", None)
        if hook is not None:
            hook(alert)

    def _record_clear(self, alert: Alert):
        tel = self.telemetry
        if tel is None:
            return
        hook = getattr(tel, "alert_cleared", None)
        if hook is not None:
            hook(alert)

    # -- readouts ----------------------------------------------------------
    def active(self) -> list:
        return [st.active for st in self._states.values()
                if st.active is not None]

    @property
    def degraded(self) -> bool:
        return bool(self.active())

    def health(self) -> dict:
        """The degraded-aware ``/healthz`` contribution: status flips to
        ``degraded`` while any alert is active (HTTP 200 either way —
        scrapers must not flap on a warning)."""
        act = self.active()
        return {
            "status": "degraded" if act else "ok",
            "active_alerts": len(act),
            "alerts": sorted(a.rule for a in act),
        }

    def report(self) -> dict:
        """The ``/alerts`` endpoint body and the bench artifact section:
        live status + per-rule fire counts + active/history records +
        rule catalog."""
        act = self.active()
        return {
            "status": "degraded" if act else "ok",
            "active_alerts": len(act),
            "fired_total": self.fired_total,
            "evaluations": self.evaluations,
            "active": [a.to_dict() for a in act],
            "history": [a.to_dict() for a in self.history],
            "rules": {r.name: dict(r.describe(),
                                   fires=self._states[r.name].fires)
                      for r in self.rules},
        }


def aggregate_alerts(sentinels) -> dict:
    """Fleet-level alert view: ``sentinels`` is ``{label: HealthSentinel}``
    (or an iterable of pairs).  Worst status wins; fire counts sum; the
    per-component reports ride side by side — the shape both the
    ``/alerts`` endpoint and the ``alerts`` artifact sections use."""
    items = sentinels.items() if hasattr(sentinels, "items") else sentinels
    components = {}
    active = 0
    fired = 0
    for label, s in items:
        rep = s.report()
        components[str(label)] = rep
        active += rep["active_alerts"]
        fired += rep["fired_total"]
    return {
        "status": "degraded" if active else "ok",
        "active_alerts": active,
        "fired_total": fired,
        "components": components,
    }
