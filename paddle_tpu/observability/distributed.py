"""Fleet-wide observability: cross-component trace stitching + aggregated
metrics (ISSUE 12 tentpole, parts a+b).

PRs 6-7 built a deep observatory scoped to ONE engine: each
:class:`~paddle_tpu.inference.paged.ServingEngine` owns a registry/tracer/
flight trio, the :class:`~paddle_tpu.serving.fleet.ReplicaFleet` router
keeps its own registry, and the :class:`AsyncFrontend` keeps a third.  A
request that crosses frontend -> router -> replica -> failover-migration
leaves three disjoint traces with no common ID.  This module closes both
gaps:

  * **Trace stitching** — one integer ``trace_id`` (``new_trace_id()``)
    threads from ``AsyncFrontend.submit()`` through router placement,
    replica admission (``submit``/``adopt``), snapshot restore, and
    failover re-decode; every component's tracer records it on the
    request's ``submitted`` event.  :class:`TraceStitcher` merges N
    component tracers into ONE Perfetto view: each component becomes a
    process (frontend / router / per-engine replica tracks, crashed
    replica generations kept as their own tracks), and Chrome flow events
    (``ph`` s/t/f, keyed by trace_id) draw arrows along each request's
    path — a failover reads as a single request timeline: frontend span
    -> replica r0 -> migration flow-event -> replica r1.
  * **Fleet aggregation** — :class:`FleetTelemetry` merges N replica
    registries plus the frontend/router registries into one labeled
    snapshot.  Histograms merge BUCKET-WISE (every registry uses the same
    log-bucket layout per metric name, so the merge is exact addition,
    not approximation — :meth:`~.metrics.Histogram.merge_from`); counters
    sum; gauges and memory series stay per-replica side-by-side.  The
    fleet-wide SLO report reads goodput straight off the merged TTFT
    histogram (``fraction_below`` at the deadline).  Powers
    ``ReplicaFleet.stats_snapshot()`` and the ``fleet`` artifact section
    ``perf/check_obs.py`` gates.

Everything here is pure host code operating on snapshots — zero jit
calls, zero device syncs, zero engine-thread work.
"""
from __future__ import annotations

import itertools
import json
import time

from .metrics import Counter, Gauge, GaugeSeries, Histogram, MetricsRegistry
from .tracing import Tracer

__all__ = ["new_trace_id", "TraceStitcher", "FleetTelemetry"]

# process-global monotonic trace-id mint: an int (Chrome flow-event ids
# bind on it), unique within the process — which is the stitching domain
# (in-process fleets share one clock AND one id space)
_TRACE_IDS = itertools.count(1)


def new_trace_id() -> int:
    """Mint a fleet-unique trace id (monotonic int)."""
    return next(_TRACE_IDS)


def _trace_id_of(tr) -> int | None:
    """The trace_id a RequestTrace carries (stamped on its ``submitted``
    event attrs by Telemetry/fleet/frontend), or None."""
    for _name, _t, attrs in tr.events:
        if attrs and "trace_id" in attrs:
            return attrs["trace_id"]
    return None


class TraceStitcher:
    """Merge per-component :class:`~.tracing.Tracer` records into one
    Perfetto/chrome-trace view with flow events binding each trace_id's
    spans across components.

    ``add(name, tracer)`` order decides process ids (pid 0 = first
    component — conventionally the frontend or router track).  Components
    may share request ids freely: tracks are (pid, tid) pairs, and the
    flow arrows bind on trace_id, not rid."""

    def __init__(self):
        self._components: list[tuple[str, Tracer]] = []

    def add(self, name: str, tracer: Tracer) -> "TraceStitcher":
        self._components.append((str(name), tracer))
        return self

    @property
    def component_names(self) -> list[str]:
        return [n for n, _ in self._components]

    def flow_chains(self) -> dict:
        """{trace_id: [(component name, t_first, t_last), ...]} ordered by
        each component's first touch — the per-request path across the
        fleet (the failover acceptance reads the crashed request's chain
        here: router -> r0 (crashed) -> r1)."""
        chains: dict = {}
        for name, tracer in self._components:
            for tr in tracer.traces():
                if not tr.events:
                    continue
                tid = _trace_id_of(tr)
                if tid is None:
                    continue
                chains.setdefault(tid, []).append(
                    (name, tr.events[0][1], tr.events[-1][1]))
        for touches in chains.values():
            touches.sort(key=lambda x: (x[1], x[2]))
        return chains

    def to_chrome_trace(self) -> dict:
        """One chrome://tracing / Perfetto-loadable dict: component i's
        events re-homed to pid i (its own named process), plus flow
        events (``ph`` s/t/f, id = trace_id) from each request's first
        touch on every component it crossed."""
        us = 1e6
        events: list[dict] = []
        # (pid, tid, t_first) per (component, trace_id) for the flows
        touches: dict = {}
        for pid, (name, tracer) in enumerate(self._components):
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": name}})
            for ev in tracer.to_chrome_trace()["traceEvents"]:
                if ev.get("ph") == "M" and ev.get("name") == "process_name":
                    continue        # replaced by the component name above
                ev = dict(ev)
                ev["pid"] = pid
                events.append(ev)
            for tr in tracer.traces():
                if not tr.events:
                    continue
                tid = _trace_id_of(tr)
                if tid is not None:
                    touches.setdefault(tid, []).append(
                        (tr.events[0][1], pid, tr.rid + 1))
        flow_events: list[dict] = []
        for tid, ts in sorted(touches.items()):
            if len(ts) < 2:
                continue            # a single-component request needs no arrow
            ts.sort()
            last = len(ts) - 1
            for i, (t0, pid, ttid) in enumerate(ts):
                ph = "s" if i == 0 else ("f" if i == last else "t")
                ev = {"name": "request", "cat": "request_flow", "ph": ph,
                      "id": int(tid), "pid": pid, "tid": ttid,
                      "ts": round(t0 * us, 3)}
                if ph == "f":
                    ev["bp"] = "e"  # bind to the enclosing slice's begin
                flow_events.append(ev)
        return {"traceEvents": events + flow_events,
                "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path

    def summary(self) -> dict:
        """Artifact-embeddable digest: event/flow counts, component list,
        and the longest per-request chain (the stitched-failover gate in
        perf/check_obs.py reads ``max_chain``)."""
        trace = self.to_chrome_trace()["traceEvents"]
        flows = [e for e in trace if e.get("cat") == "request_flow"]
        chains = self.flow_chains()
        max_chain: list[str] = []
        for touched in chains.values():
            names = [name for name, _t0, _t1 in touched]
            if len(names) > len(max_chain):
                max_chain = names
        return {
            "components": self.component_names,
            "trace_events": len(trace),
            "flow_events": len(flows),
            "requests_stitched": sum(1 for t in chains.values()
                                     if len(t) >= 2),
            "max_chain": max_chain,
        }


# ---------------------------------------------------------------------------
# Fleet metric aggregation
# ---------------------------------------------------------------------------
def _registry_of(component) -> MetricsRegistry:
    """Accept a MetricsRegistry, a Telemetry, or a ServingEngine (with
    telemetry attached) — FleetTelemetry callers hold any of the three."""
    if isinstance(component, MetricsRegistry):
        return component
    reg = getattr(component, "registry", None)
    if isinstance(reg, MetricsRegistry):
        return reg
    tel = getattr(component, "telemetry", None)
    if tel is not None and isinstance(getattr(tel, "registry", None),
                                      MetricsRegistry):
        return tel.registry
    raise TypeError(
        f"FleetTelemetry needs a MetricsRegistry / Telemetry / telemetry-"
        f"bearing engine, not {type(component).__name__}")


class FleetTelemetry:
    """Merge N labeled registries (replicas + frontend/router) into one
    fleet snapshot.

    ``components``: ``{label: MetricsRegistry | Telemetry | engine}``.
    ``frontend``: optional extra registry merged under the ``frontend``
    label (the AsyncFrontend admission controller's).  Merging reads the
    live registries at snapshot time — pure host reads, no locks the
    writers could wait on."""

    def __init__(self, components: dict, frontend=None,
                 clock=time.perf_counter):
        self._components = {str(k): _registry_of(v)
                            for k, v in dict(components).items()}
        if frontend is not None:
            self._components.setdefault("frontend", _registry_of(frontend))
        self.clock = clock

    @classmethod
    def from_fleet(cls, fleet, frontend=None,
                   clock=time.perf_counter) -> "FleetTelemetry":
        """Aggregate a live :class:`~paddle_tpu.serving.fleet.ReplicaFleet`:
        every live telemetry-bearing replica plus the fleet's own router
        registry (label ``router``)."""
        comps: dict = {}
        # drain-retired replicas (elastic scale-down) keep their final
        # registries on the fleet: their service life stays in the merged
        # fleet quantiles and the per-replica hit-rate series
        for name, reg in getattr(fleet, "_retired_telemetry", ()):
            comps[f"{name} (retired)"] = reg
        for rep in fleet._replicas:
            if rep.alive and rep.engine is not None \
                    and rep.engine.telemetry is not None:
                comps[rep.name] = rep.engine.telemetry.registry
        comps["router"] = fleet.metrics
        return cls(comps, frontend=frontend, clock=clock)

    @property
    def labels(self) -> list[str]:
        return sorted(self._components)

    def merged_histograms(self) -> dict:
        """{name: merged Histogram} — bucket-wise exact across every
        component that registers the name (same log-bucket layout by
        construction: same name, same constructor)."""
        out: dict[str, Histogram] = {}
        for label in self.labels:
            reg = self._components[label]
            for name in reg.names():
                m = reg._metrics.get(name)
                if not isinstance(m, Histogram):
                    continue
                tgt = out.get(name)
                if tgt is None:
                    tgt = Histogram(name, unit=m.unit, lo=m.lo,
                                    growth=m.growth)
                    out[name] = tgt
                tgt.merge_from(m)
        return out

    def snapshot(self) -> dict:
        """One labeled fleet snapshot:

          * ``merged`` — histograms merged bucket-wise (full quantile
            dicts) and counters summed across components;
          * ``per_replica`` — gauges, series tails, and counters
            side-by-side per label (``mem.*`` occupancy next to each
            other is the fleet memory observatory view);
          * ``replicas`` — the label list, ``at`` — snapshot clock."""
        merged: dict = {name: h.to_value()
                        for name, h in sorted(self.merged_histograms()
                                              .items())}
        counters: dict[str, int] = {}
        per_replica: dict = {}
        for label in self.labels:
            reg = self._components[label]
            side: dict = {}
            for name in reg.names():
                m = reg._metrics.get(name)
                if isinstance(m, Counter):
                    counters[name] = counters.get(name, 0) + m.value
                    side[name] = m.value
                elif isinstance(m, Gauge):
                    side[name] = m.value
                elif isinstance(m, GaugeSeries):
                    side[name] = m.to_value()
            per_replica[label] = side
        merged.update(sorted(counters.items()))
        return {"replicas": self.labels, "merged": merged,
                "per_replica": per_replica, "at": float(self.clock())}

    def slo_report(self, ttft_deadline_s: float) -> dict:
        """Fleet-wide SLO readout straight off the MERGED histograms:
        TTFT/TPOT/E2E quantiles plus goodput at the deadline via the
        merged TTFT histogram's ``fraction_below`` — exact bucket-wise,
        no per-request resampling needed."""
        m = self.merged_histograms()

        def _q(name):
            h = m.get(name)
            if h is None or not h.count:
                return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0,
                        "count": 0}
            q = h.percentiles()
            return {"p50_ms": round(q[50] * 1e3, 2),
                    "p95_ms": round(q[95] * 1e3, 2),
                    "p99_ms": round(q[99] * 1e3, 2), "count": h.count}

        h_ttft = m.get("serve.ttft_s")
        n = h_ttft.count if h_ttft is not None else 0
        frac = h_ttft.fraction_below(ttft_deadline_s) \
            if h_ttft is not None and n else 0.0
        return {
            "ttft": _q("serve.ttft_s"),
            "tpot": _q("serve.tpot_s"),
            "e2e": _q("serve.e2e_s"),
            "ttft_deadline_ms": round(ttft_deadline_s * 1e3, 2),
            "requests": n,
            "goodput_fraction": round(frac, 4),
            "on_time_requests": int(round(frac * n)),
        }
