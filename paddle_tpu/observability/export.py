"""Live metric export: Prometheus-text + JSON renderers over registry
snapshots, and a stdlib ``http.server``-on-a-thread endpoint (ISSUE 12
tentpole part c).

A million-user deployment must be debuggable WHILE it runs, not only
post-hoc from ``--json`` artifacts.  This module adds the pull side with
no new dependencies and zero engine-thread work:

  * :func:`export_snapshot` — a TYPED snapshot of a
    :class:`~.metrics.MetricsRegistry`: every metric tagged
    counter/gauge/histogram/series, histograms carrying their sparse
    cumulative buckets (so the Prometheus render has real ``_bucket``
    lines, not just quantiles).  Reading is lock-free: buckets are read
    BEFORE the count, so a concurrent ``observe()`` can never make a
    rendered series non-cumulative (torn-snapshot safety by construction).
  * :func:`render_prometheus` / :func:`render_json` — the two text
    renderers over one (or several labeled) typed snapshots; both render
    the same values, and a test pins that they agree on every one.
  * :class:`MetricsExporter` — ``ThreadingHTTPServer`` on a daemon
    thread serving ``/metrics`` (Prometheus text), ``/metrics.json``,
    ``/healthz`` (degraded-aware since ISSUE 13: status flips to
    ``degraded`` with an active-alert count while the health sentinel
    has firing rules — the HTTP code stays 200 so scrapers don't flap),
    ``/alerts`` (the sentinel report), ``/slow`` (tail-outlier dumps:
    the top-K slowest requests with their critical-path attribution),
    and ``/requests`` (recent request summaries).  Off by default, binds
    ``127.0.0.1`` by default (metrics can leak workload shape — put real
    auth in front before binding wider).  All rendering happens on the
    HTTP thread from snapshots; the serving engine thread does no
    exporter work at all.
"""
from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .metrics import Counter, Gauge, GaugeSeries, Histogram, MetricsRegistry

__all__ = ["export_snapshot", "render_prometheus", "render_json",
           "prom_name", "prom_escape_label", "MetricsExporter"]

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """Prometheus metric-name sanitization: the charset is
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` — dots (our namespace separator) and
    anything else illegal become underscores, and a leading digit gets a
    guard underscore."""
    s = _NAME_BAD.sub("_", str(name))
    if not s or s[0].isdigit():
        s = "_" + s
    return s


def prom_escape_label(value) -> str:
    """Label-VALUE escaping per the text-format spec: backslash, double
    quote, and newline must be escaped; everything else passes through."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{prom_name(k)}="{prom_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def hist_export(h: Histogram) -> dict:
    """One histogram's typed export row: the quantile summary plus the
    sparse cumulative buckets.  Buckets are read before ``count`` (see
    ``Histogram.cumulative_buckets``) so under concurrent observes the
    ``+Inf`` row — rendered from ``count`` — is always >= the last
    bucket: every render is cumulative, never torn."""
    buckets = h.cumulative_buckets()
    v = h.to_value()                 # count read after the buckets
    v["buckets"] = [[le, n] for le, n in buckets]
    return v


def export_snapshot(registry: MetricsRegistry) -> dict:
    """Typed snapshot: ``{name: {"type": ..., ...}}`` plus ``at``.  The
    type tag is what lets the renderers emit correct Prometheus metric
    types without guessing from the value shape."""
    out: dict = {}
    for name in registry.names():
        m = registry._metrics.get(name)
        if isinstance(m, Counter):
            out[name] = {"type": "counter", "value": m.value}
        elif isinstance(m, Gauge):
            out[name] = {"type": "gauge", "value": m.value}
        elif isinstance(m, Histogram):
            out[name] = {"type": "histogram", **hist_export(m)}
        elif isinstance(m, GaugeSeries):
            out[name] = {"type": "series", **m.to_value()}
    out["at"] = float(registry.clock())
    return out


def _as_labeled(snap: dict) -> dict:
    """Normalize to ``{label: typed snapshot}`` (single snapshot ->
    label '').  A typed entry at top level means single; otherwise it is
    a labeled bundle only when every non-``at`` value is itself a dict —
    an EMPTY snapshot (just ``at``, e.g. a registry scraped before its
    first metric) is a single snapshot, not a bundle of floats."""
    if any(isinstance(v, dict) and "type" in v for v in snap.values()):
        return {"": snap}
    vals = [v for k, v in snap.items() if k != "at"]
    if vals and all(isinstance(v, dict) for v in vals):
        return snap
    return {"": snap}


def render_prometheus(snapshot: dict, label_key: str = "component") -> str:
    """Prometheus text format over a typed snapshot (or a ``{label:
    snapshot}`` bundle — each sample then carries ``component="label"``).

    Counters render with the conventional ``_total`` suffix; histograms
    render ``_bucket{le=...}`` (cumulative, ``+Inf`` == count) +
    ``_sum`` + ``_count``; series render their last-sample numeric fields
    as gauges suffixed ``_last_<field>``."""
    labeled = _as_labeled(snapshot)
    # group by metric name so each # TYPE header appears exactly once
    names: dict[str, str] = {}
    for lab, snap in labeled.items():
        for name, entry in snap.items():
            if name == "at" or not isinstance(entry, dict):
                continue
            names.setdefault(name, entry.get("type", "gauge"))
    lines: list[str] = []
    for name in sorted(names):
        kind = names[name]
        base = prom_name(name)
        if kind == "counter":
            lines.append(f"# TYPE {base}_total counter")
        elif kind == "histogram":
            lines.append(f"# TYPE {base} histogram")
        elif kind in ("gauge", "series"):
            lines.append(f"# TYPE {base} gauge" if kind == "gauge"
                         else f"# TYPE {base}_last gauge")
        for lab in sorted(labeled):
            entry = labeled[lab].get(name)
            if not isinstance(entry, dict):
                continue
            lb = {label_key: lab} if lab else {}
            if kind == "counter":
                lines.append(f"{base}_total{_fmt_labels(lb)} "
                             f"{entry['value']}")
            elif kind == "gauge":
                lines.append(f"{base}{_fmt_labels(lb)} {entry['value']}")
            elif kind == "histogram":
                for le, n in entry.get("buckets", []):
                    bl = dict(lb)
                    bl["le"] = repr(float(le))
                    lines.append(f"{base}_bucket{_fmt_labels(bl)} {n}")
                bl = dict(lb)
                bl["le"] = "+Inf"
                lines.append(f"{base}_bucket{_fmt_labels(bl)} "
                             f"{entry['count']}")
                lines.append(f"{base}_sum{_fmt_labels(lb)} {entry['sum']}")
                lines.append(f"{base}_count{_fmt_labels(lb)} "
                             f"{entry['count']}")
            elif kind == "series":
                last = entry.get("last") or {}
                for field, v in sorted(last.items()):
                    if isinstance(v, bool) or not isinstance(v, (int, float)):
                        continue
                    lines.append(f"{base}_last_{prom_name(field)}"
                                 f"{_fmt_labels(lb)} {v}")
    return "\n".join(lines) + "\n"


def render_json(snapshot: dict) -> str:
    """The JSON twin of :func:`render_prometheus` — same typed snapshot,
    every value identical (a test diffs the two renders value by
    value)."""
    return json.dumps(snapshot, sort_keys=True)


class _Handler(BaseHTTPRequestHandler):
    exporter: "MetricsExporter" = None      # set per server

    # silence the default stderr access log (a scrape per second would
    # otherwise spam the serving process's output)
    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _send(self, code: int, body: str, ctype: str):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # one _Handler instance per request, owned by its server thread
    def do_GET(self):  # noqa: N802  # graftlint: owner=worker
        ex = self.server.exporter
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                ex.scrapes += 1
                self._send(200, render_prometheus(ex.snapshot_fn()),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/metrics.json":
                ex.scrapes += 1
                self._send(200, render_json(ex.snapshot_fn()),
                           "application/json")
            elif path == "/healthz":
                # degraded-aware (ISSUE 13): the health_fn (usually the
                # sentinel's health()) may flip status to "degraded" and
                # report the active-alert count — the HTTP code stays 200
                # either way so scrapers don't flap on a warning
                health = {"status": "ok", "active_alerts": 0,
                          "uptime_s": round(time.monotonic() - ex._t0, 3),
                          "scrapes": ex.scrapes}
                if ex.health_fn is not None:
                    health.update(ex.health_fn())
                self._send(200, json.dumps(health), "application/json")
            elif path == "/alerts":
                alerts = ex.alerts_fn() if ex.alerts_fn is not None \
                    else {"status": "ok", "active_alerts": 0,
                          "fired_total": 0, "components": {},
                          "note": "no health sentinel attached"}
                self._send(200, json.dumps(alerts), "application/json")
            elif path == "/slow":
                slow = ex.slow_fn() if ex.slow_fn is not None else []
                self._send(200, json.dumps(list(slow)), "application/json")
            elif path == "/requests":
                reqs = ex.requests_fn() if ex.requests_fn is not None else []
                self._send(200, json.dumps(list(reqs)), "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "unknown path", "paths": [
                        "/metrics", "/metrics.json", "/healthz",
                        "/alerts", "/slow", "/requests"]}),
                    "application/json")
        except Exception as exc:  # noqa: BLE001 — a scrape must never
            # take the server thread down; report the failure to the
            # scraper instead
            self._send(500, json.dumps({"error": f"{type(exc).__name__}: "
                                                 f"{exc}"}),
                       "application/json")

    # one _Handler instance per request, owned by its server thread
    def do_POST(self):  # noqa: N802  # graftlint: owner=worker
        """``POST /generate`` -> Server-Sent-Events token stream (the
        real-socket serving transport over :class:`~paddle_tpu.serving.
        frontend.AsyncFrontend`).  ``generate_fn(payload)`` yields
        SSE-framed strings; a client disconnect mid-stream surfaces here
        as a broken pipe, and CLOSING the generator is the cancel signal
        (its ``finally`` abandons the stream -> ``engine.cancel`` frees
        the pages mid-decode)."""
        ex = self.server.exporter
        path = self.path.split("?", 1)[0]
        if path != "/generate" or ex.generate_fn is None:
            self._send(404, json.dumps({"error": "unknown path"}),
                       "application/json")
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, TypeError) as exc:
            self._send(400, json.dumps({"error": f"bad request body: "
                                                 f"{exc}"}),
                       "application/json")
            return
        gen = ex.generate_fn(payload)
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            for chunk in gen:
                self.wfile.write(chunk.encode("utf-8"))
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream — closing the generator runs
            # its finally block, which cancels the live request
            pass
        finally:
            gen.close()


class MetricsExporter:
    """``/metrics`` + ``/healthz`` + ``/requests`` on a daemon thread.

    ``snapshot_fn`` returns a typed snapshot (:func:`export_snapshot`) or
    a ``{label: typed snapshot}`` bundle; it runs ON THE HTTP THREAD —
    the component being observed does zero exporter work.  ``port=0``
    picks a free port (read it back from ``.port``).  SECURITY: binds
    localhost by default; metrics and ``/requests`` expose workload shape
    (prompt lengths, queue depths) — front with real auth before binding
    a routable interface."""

    def __init__(self, snapshot_fn, requests_fn=None, health_fn=None,
                 alerts_fn=None, slow_fn=None, generate_fn=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.snapshot_fn = snapshot_fn
        self.requests_fn = requests_fn
        self.health_fn = health_fn
        self.alerts_fn = alerts_fn      # /alerts: the health-sentinel report
        self.slow_fn = slow_fn          # /slow: tail-outlier dumps
        self.generate_fn = generate_fn  # POST /generate: SSE token stream
        self.host = host
        self._requested_port = int(port)
        self.scrapes = 0
        self._t0 = time.monotonic()
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int | None:
        return self._server.server_address[1] if self._server else None

    @property
    def url(self) -> str | None:
        return f"http://{self.host}:{self.port}" if self._server else None

    def start(self) -> "MetricsExporter":
        if self._server is not None:
            raise RuntimeError("exporter already started")
        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), _Handler)
        self._server.exporter = self
        self._server.daemon_threads = True
        self._t0 = time.monotonic()
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="metrics-exporter", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
        self._server = None
        self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
