"""Metrics registry: counters, gauges, log-bucketed histograms.

The serving engine's observability used to be a flat dict of ad-hoc int
attributes (``ServingEngine.stats()``) plus hand-rolled ``np.percentile``
blocks scattered through bench.py.  This module is the one shared
implementation behind all of it:

  * :class:`Counter` / :class:`Gauge` — monotonic count / last-value.
  * :class:`Histogram` — log-bucketed latency histogram with
    p50/p95/p99 quantile readout.  Buckets grow geometrically
    (``growth`` per bucket, default 1.1 → ≤ ~5% relative bucket error,
    tightened further by linear interpolation inside the bucket and exact
    min/max clamping), stored sparsely, so observe() is one dict bump —
    cheap enough for per-request serving paths, never per-token.
  * :class:`MetricsRegistry` — named metric directory with
    ``snapshot()``/``delta`` semantics and an injectable ``clock`` so
    tests are deterministic.
  * :class:`EngineStats` — an immutable, flattened snapshot of
    ``ServingEngine.stats()``; ``delta(earlier)`` yields exactly the
    per-window activity (the counters are monotonic, so a delta is always
    non-negative — tests/test_observability.py pins both properties).
"""
from __future__ import annotations

import math
import numbers
import threading
import time
from collections import deque
from collections.abc import Mapping

__all__ = ["Counter", "Gauge", "GaugeSeries", "Histogram", "MetricsRegistry",
           "EngineStats"]


class Counter:
    """Monotonically increasing counter (dashboards diff it; a decrement is
    a bug and raises)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1):
        if n < 0:
            raise ValueError(f"Counter {self.name!r} cannot decrease (n={n})")
        self.value += n

    def to_value(self):
        return self.value


class Gauge:
    """Last-written value (queue depth, free pages, acceptance rate...)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v):
        self.value = float(v)

    def to_value(self):
        return self.value


class GaugeSeries:
    """Bounded time series of gauge rows — the memory observatory appends
    one row per engine step, so the flight recorder can show the
    occupancy RAMP that led to a pool-pressure event, not just the final
    value.  Each row is ``{"seq", "t", **fields}`` with ``seq`` strictly
    increasing (sample order) and ``t`` from the caller's clock; the ring
    holds the last ``capacity`` rows.  Values are normalized to plain
    python ints/floats so rows serialize straight into flight-dump JSON."""

    __slots__ = ("name", "capacity", "_rows", "_seq")

    def __init__(self, name: str, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.name = name
        self.capacity = int(capacity)
        self._rows: deque = deque(maxlen=self.capacity)
        self._seq = 0

    def __len__(self):
        return len(self._rows)

    @property
    def total_samples(self) -> int:
        """Samples ever taken (>= len(self): the ring drops the oldest)."""
        return self._seq

    def sample(self, t: float, **fields) -> dict:
        """Append one row; returns it (already normalized)."""
        self._seq += 1
        row = {"seq": self._seq, "t": float(t)}
        for k, v in fields.items():
            # exact-type fast path first: this runs at every engine-step
            # end with ~20 plain int/float fields, and the numbers.*
            # ABC isinstance checks dominate the whole sampler's cost
            # (bool subclasses int, so `type(v) is int` stays False for it)
            tv = type(v)
            if tv is int or tv is float or tv is bool or v is None:
                row[k] = v
            elif isinstance(v, numbers.Integral):
                row[k] = int(v)
            elif isinstance(v, numbers.Real):
                row[k] = float(v)
            else:
                row[k] = v
        self._rows.append(row)
        return row

    def rows(self) -> list[dict]:
        return list(self._rows)

    def tail(self, n: int) -> list[dict]:
        """The most recent n rows (the ramp a flight dump embeds)."""
        if n <= 0:
            return []
        return list(self._rows)[-n:]

    @property
    def last(self) -> dict | None:
        return self._rows[-1] if self._rows else None

    def reset(self):
        """Drop the rows (a measurement-window boundary); ``seq`` keeps
        counting so sample order stays globally monotonic across windows."""
        self._rows.clear()

    def field_minmax(self, field: str) -> tuple[float, float] | None:
        """(min, max) of a numeric field over the retained rows."""
        vals = [r[field] for r in self._rows
                if isinstance(r.get(field), (int, float))
                and not isinstance(r.get(field), bool)]
        if not vals:
            return None
        return min(vals), max(vals)

    def to_value(self) -> dict:
        return {"count": len(self._rows), "total_samples": self._seq,
                "last": self.last}


class Histogram:
    """Log-bucketed histogram with quantile readout.

    Bucket 0 holds values ``<= lo``; bucket k (k >= 1) holds
    ``(lo * growth**(k-1), lo * growth**k]``.  Quantiles interpolate
    linearly inside the winning bucket and clamp to the exact observed
    [min, max], so small-sample readouts stay sane (a 1-sample histogram
    reports that sample for every quantile)."""

    __slots__ = ("name", "unit", "lo", "growth", "_log_g", "count", "total",
                 "min", "max", "_buckets")

    def __init__(self, name: str, unit: str = "s", lo: float = 1e-6,
                 growth: float = 1.1):
        if lo <= 0 or growth <= 1.0:
            raise ValueError("lo must be > 0 and growth > 1.0")
        self.name = name
        self.unit = unit
        self.lo = float(lo)
        self.growth = float(growth)
        self._log_g = math.log(self.growth)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._buckets: dict[int, int] = {}

    def reset(self):
        """Drop every observation (a measurement-window boundary — e.g.
        `Telemetry.reset_window()` between a bench's warm pass and its
        timed pass, so quantiles describe the window, not the compiles)."""
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._buckets.clear()

    def observe(self, v):
        v = float(v)
        self.count += 1
        self.total += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if v <= self.lo:
            idx = 0
        else:
            idx = max(1, math.ceil(math.log(v / self.lo) / self._log_g))
        self._buckets[idx] = self._buckets.get(idx, 0) + 1

    def _bounds(self, idx: int) -> tuple[float, float]:
        if idx == 0:
            return 0.0, self.lo
        return self.lo * self.growth ** (idx - 1), self.lo * self.growth ** idx

    def quantile(self, q: float) -> float:
        """Value at quantile q in [0, 1] (0 when empty)."""
        if self.count == 0:
            return 0.0
        if q <= 0.0:
            return self.min
        target = min(self.count, q * self.count)
        cum = 0
        for idx in sorted(self._buckets):
            n = self._buckets[idx]
            if cum + n >= target:
                b_lo, b_hi = self._bounds(idx)
                frac = (target - cum) / n
                val = b_lo + frac * (b_hi - b_lo)
                return min(max(val, self.min), self.max)
            cum += n
        return self.max

    def percentiles(self, ps=(50, 95, 99)) -> dict:
        return {p: self.quantile(p / 100.0) for p in ps}

    def fraction_below(self, x) -> float:
        """Fraction of observations <= x (bucket-interpolated) — the
        goodput readout for 'how many requests met the deadline'."""
        if self.count == 0:
            return 0.0
        x = float(x)
        if x >= self.max:
            return 1.0
        if x < self.min:
            return 0.0
        cum = 0
        for idx in sorted(self._buckets):
            b_lo, b_hi = self._bounds(idx)
            n = self._buckets[idx]
            if x >= b_hi:
                cum += n
                continue
            if x > b_lo:
                cum += n * (x - b_lo) / (b_hi - b_lo)
            break
        return min(1.0, cum / self.count)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def same_layout(self, other: "Histogram") -> bool:
        return (self.lo, self.growth) == (other.lo, other.growth)

    def merge_from(self, other: "Histogram") -> "Histogram":
        """Fold another histogram's observations into this one.  Exact
        when both share the same (lo, growth) bucket layout — every
        observation lands in the identical bucket index either way, so a
        fleet-wide merge of N replica histograms is bucket-wise addition,
        not an approximation (the FleetTelemetry aggregation rail)."""
        if not self.same_layout(other):
            raise ValueError(
                f"histogram {self.name!r} (lo={self.lo}, "
                f"growth={self.growth}) cannot merge bucket-wise with "
                f"{other.name!r} (lo={other.lo}, growth={other.growth}) — "
                f"layouts differ")
        self.count += other.count
        self.total += other.total
        if other.count:
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        for idx, n in list(other._buckets.items()):
            self._buckets[idx] = self._buckets.get(idx, 0) + n
        return self

    def cumulative_buckets(self) -> list[tuple[float, int]]:
        """Sparse cumulative bucket readout: ascending (upper_bound,
        cumulative_count) pairs over the non-empty buckets — the
        Prometheus ``_bucket{le=...}`` series (the exporter appends the
        ``+Inf`` row from ``count``, read AFTER the buckets so a
        concurrent observe can never make the series non-cumulative)."""
        items = sorted(list(self._buckets.items()))
        out = []
        cum = 0
        for idx, n in items:
            cum += n
            out.append((self._bounds(idx)[1], cum))
        return out

    def to_value(self) -> dict:
        p = self.percentiles()
        return {
            "count": self.count,
            "sum": round(self.total, 9),
            "mean": round(self.mean, 9),
            "min": round(self.min, 9) if self.count else 0.0,
            "max": round(self.max, 9) if self.count else 0.0,
            "p50": round(p[50], 9),
            "p95": round(p[95], 9),
            "p99": round(p[99], 9),
            "unit": self.unit,
        }


class MetricsRegistry:
    """Named metric directory.  ``clock`` is injectable (tests pass a fake
    counter and get deterministic timestamps everywhere downstream —
    Telemetry threads the same clock through tracing and the flight
    recorder)."""

    def __init__(self, clock=time.perf_counter):
        self.clock = clock
        self._metrics: dict[str, object] = {}
        self._frozen = False

    def freeze(self):
        """Registry-freeze invariant: after warmup every hot-path metric
        must already exist, so any metric-created-at-first-use from a
        NON-main thread raises from here on.  Metric-at-first-use is a
        registry mutation; once writer threads (the frontend worker, an
        exporter scrape, an async checkpoint writer) are live, a lazy
        first-use from one of them races every concurrent reader — the
        generalization of the PR 7 ckpt pre-registration fix.  Reads and
        observes of EXISTING metrics stay lock-free and legal from any
        thread; main-thread creation (tests, late wiring) stays allowed."""
        self._frozen = True

    @property
    def frozen(self) -> bool:
        return self._frozen

    def _get(self, name, cls, **kw):
        m = self._metrics.get(name)
        if m is None:
            if self._frozen and \
                    threading.current_thread() is not threading.main_thread():
                raise RuntimeError(
                    f"MetricsRegistry is frozen: metric {name!r} would be "
                    f"created at first use from non-main thread "
                    f"{threading.current_thread().name!r} — pre-register it "
                    f"before the writer threads start (registry-freeze "
                    f"invariant)")
            m = cls(name, **kw)
            self._metrics[name] = m
        elif not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, **kw) -> Histogram:
        return self._get(name, Histogram, **kw)

    def series(self, name: str, **kw) -> GaugeSeries:
        return self._get(name, GaugeSeries, **kw)

    def names(self):
        return sorted(self._metrics)

    def __contains__(self, name):
        return name in self._metrics

    def snapshot(self) -> dict:
        """{metric name: value} — ints for counters, floats for gauges,
        a stats dict (count/sum/min/max/p50/p95/p99) for histograms; plus
        the snapshot clock under ``"at"``.  The items are copied before
        sorting so a metric registered concurrently (e.g. an async
        checkpoint writer's phase report) cannot tear the iteration."""
        out = {name: m.to_value()
               for name, m in sorted(list(self._metrics.items()))}
        out["at"] = float(self.clock())
        return out


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, prefix=f"{key}."))
        elif isinstance(v, (int, float, bool)):
            out[key] = v
    return out


class EngineStats(Mapping):
    """Immutable flattened snapshot of ``ServingEngine.stats()`` (nested
    dicts dotted: ``jit_cache_misses.prefill``).  ``delta(earlier)``
    returns per-window activity over the integer counters — ratios
    (``draft_accept_rate``) are snapshot-only and excluded from deltas."""

    __slots__ = ("_v", "at")

    def __init__(self, values: dict, at: float):
        self._v = dict(values)
        self.at = float(at)

    @classmethod
    def capture(cls, stats: dict, clock=time.perf_counter) -> "EngineStats":
        return cls(_flatten(stats), clock())

    # Mapping interface ----------------------------------------------------
    def __getitem__(self, k):
        return self._v[k]

    def __iter__(self):
        return iter(self._v)

    def __len__(self):
        return len(self._v)

    def counters(self) -> dict:
        """The integer (monotonic) subset."""
        return {k: v for k, v in self._v.items()
                if isinstance(v, int) and not isinstance(v, bool)}

    def delta(self, earlier: "EngineStats") -> dict:
        """Per-window activity: this snapshot's counters minus an earlier
        snapshot's (missing earlier keys count from 0 — e.g. a jit fn
        compiled for the first time inside the window).  Includes
        ``window_s``, the clock span between the snapshots."""
        mine = self.counters()
        theirs = earlier.counters()
        out = {k: v - theirs.get(k, 0) for k, v in mine.items()}
        out["window_s"] = self.at - earlier.at
        return out

    def __repr__(self):
        return f"EngineStats(at={self.at:.6f}, {self._v!r})"
