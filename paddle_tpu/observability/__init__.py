"""Serving observability: metrics registry, request-lifecycle tracing,
crash flight recorder, SLO reporting (README §Observability).

Three pieces, one clock:

  * :mod:`.metrics` — counters / gauges / log-bucketed histograms with
    p50/p95/p99 readout, a named registry with snapshot semantics, and
    :class:`EngineStats` (flattened ``ServingEngine.stats()`` snapshots
    with exact per-window ``delta()``).
  * :mod:`.tracing` — per-request ordered lifecycle event records +
    engine phase spans, exportable as Chrome-trace/Perfetto JSON and
    bridgeable into jax device traces via ``paddle_tpu.profiler``.
  * :mod:`.flight` — a bounded ring of recent engine events that dumps
    automatically on stalls, recompile-budget failures, preemption
    storms, and injected faults.

:class:`.telemetry.Telemetry` bundles all three for the serving engine
(``ServingEngine(..., telemetry=True)``) and adds the ISSUE 7
observatory: host/device step decomposition
(:meth:`~.telemetry.Telemetry.utilization_report`), the per-step PagePool
memory series (``mem.pool`` :class:`~.metrics.GaugeSeries`, ramp-embedded
in flight dumps, Perfetto counter tracks), and jit-compile accounting
(``engine.compile_s``).  :class:`.train.TrainTelemetry` is the same bundle
shaped for the training loop (``TrainStep`` / ``Model.fit`` /
``CheckpointManager``: step/data/compute timing, checkpoint spans,
nonfinite + torn-snapshot flight events with FaultPlan context).
Telemetry off (the default) is a no-op fast path — one flag check per
hook site, zero per-token work."""
from .attribution import (CriticalPath, TailRecorder, attribute,
                          attribute_stitched, attribution_report,
                          merge_tail_dumps, stitched_attribution_report)
from .distributed import FleetTelemetry, TraceStitcher, new_trace_id
from .export import (MetricsExporter, export_snapshot, render_json,
                     render_prometheus)
from .flight import FlightRecorder
from .health import (Alert, AlertRule, BurnRateRule, DeltaRule,
                     HealthSentinel, RatioDeltaRule, TrendRule,
                     aggregate_alerts, autoscale_rules, default_rules)
from .metrics import (Counter, EngineStats, Gauge, GaugeSeries, Histogram,
                      MetricsRegistry)
from .slo import burn_rate, latency_percentiles, slo_report, windowed_burn
from .telemetry import Telemetry
from .tracing import RequestTrace, Tracer
from .train import TrainTelemetry, fault_context

__all__ = ["Counter", "Gauge", "GaugeSeries", "Histogram", "MetricsRegistry",
           "EngineStats", "Tracer", "RequestTrace", "FlightRecorder",
           "Telemetry", "TrainTelemetry", "fault_context",
           "latency_percentiles", "slo_report",
           # fleet-wide observability plane (ISSUE 12)
           "FleetTelemetry", "TraceStitcher", "new_trace_id",
           "MetricsExporter", "export_snapshot", "render_prometheus",
           "render_json",
           # latency forensics + health sentinel (ISSUE 13)
           "CriticalPath", "attribute", "attribute_stitched",
           "attribution_report", "stitched_attribution_report",
           "TailRecorder", "merge_tail_dumps",
           "Alert", "AlertRule", "TrendRule", "DeltaRule", "RatioDeltaRule",
           "BurnRateRule", "HealthSentinel", "default_rules",
           "autoscale_rules", "aggregate_alerts", "burn_rate",
           "windowed_burn"]
