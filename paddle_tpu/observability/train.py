"""Training-loop telemetry: step/data-wait timing, resilience counters,
checkpoint spans, and a flight record of nonfinite/torn-snapshot events.

The serving engine got its observatory in PR 6; this is the same three
pieces (metrics registry, tracer, flight recorder) shaped for the TRAINING
loop — ``TrainStep``, ``hapi.Model.fit``, and ``CheckpointManager`` all
accept a :class:`TrainTelemetry` and hook it at existing host boundaries
only:

  histograms (seconds): ``train.step_s`` (one fit/TrainStep iteration,
    host wall — a real device time only where the loop already syncs,
    e.g. the nonfinite guard's flag fetch or ``float(loss)``),
    ``train.data_s`` (fit's wait on the data loader), ``train.compute_s``
    (fit's train_batch call), ``ckpt.save_s`` / ``ckpt.stage_s`` /
    ``ckpt.commit_s`` / ``ckpt.restore_s`` (checkpoint spans).
  counters: ``train.steps``, ``train.samples``,
    ``train.nonfinite_skips``, ``train.nonfinite_raises``,
    ``train.scaler_backoffs``, ``ckpt.saves``, ``ckpt.restores``,
    ``ckpt.torn_snapshots``.

Resilience events land in the flight recorder WITH the active
:class:`~paddle_tpu.resilience.faults.FaultPlan` context (seed, specs,
fire counts), so a postmortem of a chaos run shows which injected fault
produced the skip/torn snapshot it is looking at.  ``nonfinite_raise``
additionally auto-dumps the ring — the crash artifact for a diverged run.

Telemetry off (the default everywhere) is a no-op: one ``is not None``
check per hook site, zero work, training numerics untouched either way
(the hooks read host timestamps and already-fetched host values only —
``tests/test_observability.py`` asserts fit losses bit-exact on vs off).
"""
from __future__ import annotations

import contextlib
import time
from collections import deque

from .flight import FlightRecorder
from .metrics import MetricsRegistry
from .tracing import Tracer

__all__ = ["TrainTelemetry", "fault_context", "batch_samples"]


def batch_samples(x) -> int:
    """Leading-dim sample count of one batch input (0 when unknowable —
    scalars, 0-d arrays, non-arrays) — shape metadata only, never a device
    sync.  Shared by ``Model.fit`` and ``TrainStep`` so a 0-d batch arg
    cannot crash the telemetry-on path that telemetry-off survives."""
    first = x[0] if isinstance(x, (list, tuple)) and x else x
    shape = getattr(first, "shape", None)
    try:
        return int(shape[0]) if shape else 0
    except (TypeError, IndexError):
        return 0


def fault_context() -> dict | None:
    """The active FaultPlan, summarized for a flight event (None outside
    an ``inject()`` scope): seed, spec list, hit/fire counts — enough to
    tie a recorded skip/torn-snapshot to the drill that injected it."""
    from ..resilience.faults import active_plan
    plan = active_plan()
    if plan is None:
        return None
    return {"seed": plan.seed,
            "specs": [f"{s.point}:{s.action}" for s in plan.specs],
            "hits": plan.hits(), "fired": plan.fired()}


class TrainTelemetry:
    """Telemetry bundle for one training job: pass to
    ``TrainStep(..., telemetry=...)``, ``Model.fit(..., telemetry=...)``,
    and ``CheckpointManager(..., telemetry=...)`` (sharing one instance
    gives one clock domain and one flight record across all three)."""

    def __init__(self, clock=time.perf_counter, flight_capacity: int = 256,
                 flight_dump_path: str | None = None,
                 max_engine_events: int = 8192):
        self.clock = clock
        self.registry = MetricsRegistry(clock=clock)
        self.tracer = Tracer(clock=clock,
                             max_engine_events=max_engine_events)
        self.flight = FlightRecorder(capacity=flight_capacity, clock=clock,
                                     dump_path=flight_dump_path)
        r = self.registry
        self._h_step = r.histogram("train.step_s")
        self._h_data = r.histogram("train.data_s")
        self._h_compute = r.histogram("train.compute_s")
        self._c_steps = r.counter("train.steps")
        self._c_samples = r.counter("train.samples")
        self._c_skips = r.counter("train.nonfinite_skips")
        self._c_raises = r.counter("train.nonfinite_raises")
        self._c_backoffs = r.counter("train.scaler_backoffs")
        self._c_saves = r.counter("ckpt.saves")
        self._c_restores = r.counter("ckpt.restores")
        self._c_torn = r.counter("ckpt.torn_snapshots")
        self._c_async_fail = r.counter("ckpt.async_save_failures")
        # pre-register the checkpoint span/phase histograms: an ASYNC save
        # reports ckpt.stage/ckpt.commit from the writer thread, and the
        # phase_event fast path must then be a read-only dict get — never
        # a lazy insert into the registry while the training thread reads
        # or extends it
        for nm in ("ckpt.save_s", "ckpt.stage_s", "ckpt.commit_s",
                   "ckpt.restore_s"):
            r.histogram(nm)
        # bounded recent step summaries (throughput windows, debugging)
        self.step_log: deque[dict] = deque(maxlen=4096)
        self._win_samples = 0      # samples within the current window

    # -- train loop hooks --------------------------------------------------
    def step(self, dur_s: float, data_s: float | None = None,
             compute_s: float | None = None, samples: int = 0,
             good: bool = True):
        """One training iteration: total host wall `dur_s`, optionally
        split into data wait vs compute (fit measures both; a bare
        TrainStep only knows its own dispatch time)."""
        self._c_steps.inc()
        self._h_step.observe(dur_s)
        if data_s is not None:
            self._h_data.observe(data_s)
        if compute_s is not None:
            self._h_compute.observe(compute_s)
        if samples:
            self._c_samples.inc(int(samples))
            self._win_samples += int(samples)
        self.step_log.append({"t": self.clock(), "dur_s": float(dur_s),
                              "data_s": data_s, "compute_s": compute_s,
                              "samples": int(samples), "good": bool(good)})

    def nonfinite_skip(self, step: int, consecutive: int):
        """TrainStep's guard skipped a non-finite step (params untouched)."""
        self._c_skips.inc()
        self.flight.record("nonfinite_skip", step=int(step),
                           consecutive=int(consecutive),
                           fault_plan=fault_context())

    def nonfinite_raise(self, step: int, consecutive: int,
                        skipped_total: int) -> dict:
        """The guard gave up (M consecutive bad steps): record + auto-dump
        the flight ring — the postmortem artifact for a diverged run."""
        self._c_raises.inc()
        self.flight.record("nonfinite_raise", step=int(step),
                           consecutive=int(consecutive),
                           fault_plan=fault_context())
        return self.flight.dump("nonfinite_raise", step=int(step),
                                consecutive=int(consecutive),
                                skipped_total=int(skipped_total))

    def scaler_backoff(self, step: int):
        """GradScaler dynamic-loss-scale backoff on a skipped step."""
        self._c_backoffs.inc()
        self.flight.record("scaler_backoff", step=int(step))

    # -- checkpoint hooks --------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Timed span around a checkpoint (or any) operation: lands in the
        ``<name>_s`` histogram, the tracer's engine track, and the flight
        record — exception-safe (the span closes either way, with
        ``ok=False`` on the error path)."""
        t0 = self.clock()
        ok = True
        try:
            yield
        except BaseException:
            ok = False
            raise
        finally:
            t1 = self.clock()
            self.registry.histogram(f"{name}_s").observe(t1 - t0)
            self.tracer.engine_span(name, t0, t1, ok=ok, **attrs)
            self.flight.record(name, dur_s=round(t1 - t0, 6), ok=ok,
                               **attrs)

    def phase_event(self, name: str, dur_s: float, **attrs):
        """A sub-phase measured by the callee (the checkpoint writer's
        stage/commit durations ride `save_state_dict(on_phase=...)`)."""
        self.registry.histogram(f"{name}_s").observe(dur_s)
        self.flight.record(name, dur_s=round(float(dur_s), 6), **attrs)

    def saved(self, step: int, path: str):
        self._c_saves.inc()
        self.flight.record("ckpt.saved", step=int(step), path=str(path))

    def restored(self, step, path: str):
        """A successful restore — the flight record says WHICH snapshot a
        resumed run actually loaded (the postmortem question)."""
        self._c_restores.inc()
        self.flight.record("ckpt.restored", step=int(step),
                           path=str(path))

    def async_save_failed(self, error):
        """A pipelined background save died — detected at the NEXT
        ``wait()``/``save()`` entry, so the failure is on the record even
        though the launching span already closed ok=True (async spans
        measure launch + snapshot capture; durability is only confirmed at
        the next drain)."""
        self._c_async_fail.inc()
        self.flight.record("ckpt.async_save_failed",
                           error=str(error)[:200],
                           fault_plan=fault_context())

    def torn_snapshot(self, path: str, error):
        """A snapshot failed manifest verification during discovery —
        recorded with the fault context so chaos-sweep postmortems tie the
        rejection to the injected ckpt.write/commit fault that tore it."""
        self._c_torn.inc()
        self.flight.record("torn_snapshot", path=str(path),
                           error=str(error)[:200],
                           fault_plan=fault_context())

    # -- readouts ----------------------------------------------------------
    def snapshot(self) -> dict:
        return self.registry.snapshot()

    def report(self, window_s: float | None = None) -> dict:
        """train.* summary: step/data/compute quantiles, the data-wait vs
        compute split, skip/backoff counters, and throughput when the
        measurement wall clock is given.  ``steps``/``samples`` and the
        derived throughput are WINDOW-scoped (what the histograms hold
        since the last :meth:`reset_window`) so dividing by ``window_s``
        is internally consistent; the engine-lifetime totals ride along as
        ``total_steps``/``total_samples``."""
        def _q(h):
            q = h.percentiles()
            return {"p50_ms": round(q[50] * 1e3, 3),
                    "p95_ms": round(q[95] * 1e3, 3),
                    "p99_ms": round(q[99] * 1e3, 3),
                    "mean_ms": round(h.mean * 1e3, 3), "count": h.count}

        busy = self._h_data.total + self._h_compute.total
        rep = {
            "steps": self._h_step.count,
            "samples": self._win_samples,
            "total_steps": self._c_steps.value,
            "total_samples": self._c_samples.value,
            "step_s": _q(self._h_step),
            "data_s": _q(self._h_data),
            "compute_s": _q(self._h_compute),
            "data_wait_frac": round(self._h_data.total / busy, 4)
            if busy else 0.0,
            "nonfinite_skips": self._c_skips.value,
            "nonfinite_raises": self._c_raises.value,
            "scaler_backoffs": self._c_backoffs.value,
            "ckpt": {"saves": self._c_saves.value,
                     "restores": self._c_restores.value,
                     "torn_snapshots": self._c_torn.value,
                     "async_save_failures": self._c_async_fail.value},
        }
        if window_s is not None and window_s > 0:
            rep["window_s"] = round(float(window_s), 6)
            rep["steps_per_sec"] = round(self._h_step.count / window_s, 3)
            rep["samples_per_sec"] = round(
                self._win_samples / window_s, 2)
        return rep

    def reset_window(self):
        """Measurement-window boundary: reset the step/data/compute
        histograms, the windowed sample count, and the step log; counters
        and the flight/trace record stay cumulative (same contract as the
        serving Telemetry)."""
        for h in (self._h_step, self._h_data, self._h_compute):
            h.reset()
        self._win_samples = 0
        self.step_log.clear()
