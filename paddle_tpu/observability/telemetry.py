"""Telemetry facade the serving engine threads through its step loop.

One object bundles the three observability pieces (metrics registry,
request-lifecycle tracer, crash flight recorder) behind engine-shaped
methods, so ``inference/paged.py`` stays readable: every hook site in the
engine is one ``if tel is not None:`` flag check — telemetry OFF is a
no-op fast path with zero per-token Python work, telemetry ON records at
existing host-sync boundaries only (no new device round-trips; graftlint
SYNC001 stays clean and the jit variant counts are untouched — telemetry
is pure host code).

Metric catalog (README §Observability):

  histograms (seconds): ``serve.ttft_s``, ``serve.tpot_s``,
    ``serve.queue_s``, ``serve.prefill_s``, ``serve.e2e_s``,
    ``engine.step_host_s``, ``engine.compile_s`` (per compile-cache miss:
    compile + first run), ``engine.phase.<name>_s`` for phases
    ``sched`` (retire+admit host work), ``prefill_chunk``,
    ``decode_dispatch`` / ``decode_sync`` / ``decode_record``,
    ``verify_dispatch`` / ``verify_sync`` / ``verify_record``, and — on
    a double-buffered engine (``overlap=True``) — ``overlap_dispatch``
    / ``overlap_sync`` / ``overlap_record`` (dispatch issue, the
    one batched drain fetch, and the host replay of the drained step);
    the suffix convention keeps them in the right
    ``utilization_report`` buckets automatically
  counters: ``serve.requests_submitted``, ``serve.requests_retired``,
    ``serve.requests_timed_out``, ``serve.rejections``,
    ``serve.preemptions``, ``serve.cache_evictions``, ``serve.cow_copies``,
    ``serve.flight_dumps``, ``engine.compiles``
  gauges + series: ``mem.pool_free_pages``, ``mem.pool_occupancy_frac``,
    ``mem.fragmentation_frac``, ``mem.cache_page_refs``,
    ``mem.queue_depth`` (last value), and ``mem.pool`` — the per-step
    memory-observatory :class:`~.metrics.GaugeSeries` whose tail rides
    every flight dump as the occupancy ramp
  derived reports: :meth:`Telemetry.utilization_report` (host / dispatch /
    device-wait / gap step decomposition), :meth:`Telemetry.memory_report`,
    :meth:`Telemetry.compile_report`

Flight-recorder event ladder (the degradation-ladder events land in the
ring in the order the engine walks the rungs): ``submit`` -> ``admit`` ->
``evict`` -> ``preempt`` (+ ``reject``, ``timeout``, ``fault``, ``step``,
``retire``, ``cow``).  Dumps fire automatically on ``EngineStalledError``,
``RecompileBudgetError``, preemption storms (``storm_threshold``
preemptions within ``storm_window`` engine steps), and injected faults."""
from __future__ import annotations

import time
from collections import deque

from .attribution import TailRecorder, attribution_report
from .flight import FlightRecorder
from .metrics import MetricsRegistry
from .slo import slo_report
from .tracing import NULL_CONTEXT, Tracer

__all__ = ["Telemetry", "ENGINE_PHASES"]

# every phase name the engine can emit, pre-registered at construction so
# the registry-freeze invariant holds: once a frontend worker / exporter
# thread is live, `engine.phase.<name>_s` must never be created at first
# use from that thread (MetricsRegistry.freeze raises there)
ENGINE_PHASES = ("sched", "prefill_dense", "prefill_chunk",
                 "decode_dispatch", "decode_sync", "decode_record",
                 "verify_dispatch", "verify_sync", "verify_record",
                 "overlap_dispatch", "overlap_sync", "overlap_record",
                 "overlap_join_sync")


class Telemetry:
    """Serving-engine telemetry: pass ``telemetry=Telemetry(...)`` (or
    ``telemetry=True`` for defaults) to :class:`ServingEngine`.

    ``clock`` is injectable for deterministic tests and is shared by the
    registry, tracer, and flight recorder, so one fake clock drives every
    timestamp.  ``profiler_bridge=True`` additionally wraps engine
    dispatch phases in ``paddle_tpu.profiler`` annotations."""

    def __init__(self, clock=time.perf_counter, flight_capacity: int = 256,
                 flight_dump_path: str | None = None,
                 storm_threshold: int = 4, storm_window: int = 32,
                 profiler_bridge: bool = False, max_completed: int = 4096,
                 mem_series_capacity: int = 4096, mem_ramp_events: int = 64,
                 sentinel=None, tail_k: int = 8):
        self.clock = clock
        self.registry = MetricsRegistry(clock=clock)
        self.tracer = Tracer(clock=clock, bridge=profiler_bridge,
                             max_completed=max_completed)
        self.flight = FlightRecorder(capacity=flight_capacity, clock=clock,
                                     dump_path=flight_dump_path)
        self.storm_threshold = int(storm_threshold)
        self.storm_window = int(storm_window)
        self._preempt_steps: deque[int] = deque()
        self._storm_dumped_at = -(1 << 60)   # "never" (one dump per storm)
        # per-request summaries for exact SLO/goodput accounting (bounded)
        self.request_summaries: deque[dict] = deque(maxlen=max_completed)
        r = self.registry
        self._h_ttft = r.histogram("serve.ttft_s")
        self._h_tpot = r.histogram("serve.tpot_s")
        self._h_queue = r.histogram("serve.queue_s")
        self._h_prefill = r.histogram("serve.prefill_s")
        self._h_e2e = r.histogram("serve.e2e_s")
        self._h_step = r.histogram("engine.step_host_s")
        # tokens per prefill dispatch, WINDOW-scoped like the phase
        # histograms (reset together): its total over the prefill phase
        # totals is the windowed prefill tokens/s the admission
        # predictor needs — the engine's prefill_tokens counter is
        # lifetime-cumulative and would inflate the rate after any
        # reset_window()
        self._h_prefill_tok = r.histogram(
            "engine.prefill_tokens_per_dispatch", unit="tokens", lo=1.0)
        self._phase_h = {}
        # pre-register every engine phase histogram (registry-freeze
        # invariant: phase() must never CREATE a metric from a worker
        # thread after freeze() — it only fetches these).  _phase_h stays
        # lazy so utilization_report keeps listing only phases that ran.
        for name in ENGINE_PHASES:
            r.histogram(f"engine.phase.{name}_s")
        self._c_submitted = r.counter("serve.requests_submitted")
        self._c_retired = r.counter("serve.requests_retired")
        self._c_timed_out = r.counter("serve.requests_timed_out")
        self._c_rejections = r.counter("serve.rejections")
        self._c_preemptions = r.counter("serve.preemptions")
        self._c_evictions = r.counter("serve.cache_evictions")
        self._c_cow = r.counter("serve.cow_copies")
        self._c_dumps = r.counter("serve.flight_dumps")
        # compile accounting: fed by the analysis.sanitize instrumentation
        # that already wraps every engine executable (engine `_jit()` passes
        # `on_miss=` through to `instrument`) — a compile-cache miss lands
        # here with its wall cost, so the artifact shows WHERE warm-up time
        # went and a steady-state miss is visible in the flight record
        self._h_compile = r.histogram("engine.compile_s")
        self._c_compiles = r.counter("engine.compiles")
        self._compiles: dict[str, dict] = {}
        # memory observatory: one GaugeSeries row per engine step (pool
        # occupancy / fragmentation / cache / queue), sampled at the step's
        # END — an existing host boundary, no device sync; flight dumps
        # embed the tail of this series as the occupancy RAMP
        self.memory = r.series("mem.pool", capacity=mem_series_capacity)
        self.mem_ramp_events = int(mem_ramp_events)
        self._g_free = r.gauge("mem.pool_free_pages")
        self._g_occ = r.gauge("mem.pool_occupancy_frac")
        self._g_frag = r.gauge("mem.fragmentation_frac")
        self._g_cache = r.gauge("mem.cache_page_refs")
        self._g_queue = r.gauge("mem.queue_depth")
        # BYTES, not just page counts: pages × page_bytes for the engine's
        # active kv_dtype — the gauge a quantized page store (ROADMAP item
        # 2) moves, where a page count alone would hide the capacity win
        self._g_alloc_bytes = r.gauge("mem.pool_allocated_bytes")
        self._g_cap_bytes = r.gauge("mem.pool_capacity_bytes")
        # double-buffered host loop: decode dispatches in flight at the
        # step's end (0 on a synchronous engine, 0/1 at depth 1) — the
        # liveness companion to the engine.phase.overlap_* histograms
        self._g_inflight = r.gauge("engine.inflight_depth")
        self._device = None      # lazy jax device handle; False = no stats
        self._nested_dispatch_s = 0.0   # dispatch time inside a sched span
        # -- latency forensics + health sentinel (ISSUE 13) ----------------
        # tail-outlier capture: the top-K slowest requests auto-captured at
        # retirement with span chain + attribution + engine-state context
        # (O(log K) heap check per retire; OFF with tail_k=0)
        self.tail = TailRecorder(k=tail_k, clock=clock) if tail_k else None
        # health-sentinel metrics pre-registered (registry-freeze
        # invariant: a fire from the engine worker thread must never
        # create a metric)
        self._c_alerts = r.counter("health.alerts_fired")
        self._g_active_alerts = r.gauge("health.active_alerts")
        # the sentinel itself: evaluation rides step_done (right after the
        # memory-observatory sample), so telemetry-off engines pay nothing
        # and sentinel-off telemetry pays one None check per step
        self.sentinel = None
        if sentinel is not None:
            self.attach_sentinel(sentinel)

    # -- health sentinel (ISSUE 13) ----------------------------------------
    def attach_sentinel(self, sentinel) -> "Telemetry":
        """Attach a :class:`~paddle_tpu.observability.health.
        HealthSentinel`: it adopts this telemetry's clock (one clock
        domain) and evaluates at every engine-step end via the existing
        ``step_done`` hook — zero new jits, telemetry-off stays
        zero-cost."""
        self.sentinel = sentinel.attach(self)
        return self

    def alert_fired(self, alert):
        """Sentinel fire callback: count it, flight-record it with the
        active fault-plan context, and auto-dump the ring — the
        postmortem artifact shows the ramp that tripped the rule."""
        from .train import fault_context
        self._c_alerts.inc()
        self._g_active_alerts.set(
            len(self.sentinel.active()) if self.sentinel is not None else 1)
        self.flight.record("alert", rule=alert.rule,
                           severity=alert.severity,
                           value=round(alert.value, 6),
                           threshold=alert.threshold,
                           fault_plan=fault_context())
        self._dump("alert", rule=alert.rule, value=round(alert.value, 6),
                   threshold=alert.threshold, severity=alert.severity)

    def alert_cleared(self, alert):
        self._g_active_alerts.set(
            len(self.sentinel.active()) if self.sentinel is not None else 0)
        self.flight.record("alert_cleared", rule=alert.rule,
                           value=round(alert.value, 6))

    def attribution_report(self, top_k: int = 5) -> dict:
        """Aggregate critical-path attribution over every completed
        request on this engine's tracer (observability.attribution)."""
        return attribution_report(self.tracer, top_k=top_k)

    # -- low-level ---------------------------------------------------------
    def phase(self, name: str, t0: float, t1: float, **attrs):
        h = self._phase_h.get(name)
        if h is None:
            h = self.registry.histogram(f"engine.phase.{name}_s")
            self._phase_h[name] = h
        h.observe(t1 - t0)
        self.tracer.engine_span(name, t0, t1, **attrs)

    def sched_begin(self) -> float:
        """Start of a step's scheduling window (deadline sweep +
        admissions); returns the start timestamp.  Admission can run
        prefill DISPATCHES inside this window — they record their own
        phase spans and accumulate into ``_nested_dispatch_s``, which
        :meth:`sched_done` subtracts so the ``sched`` histogram holds pure
        host scheduling time and the utilization buckets stay DISJOINT
        (no second-counted seconds)."""
        self._nested_dispatch_s = 0.0
        return self.clock()

    def sched_done(self, t0: float, t1: float):
        nested = self._nested_dispatch_s
        self._nested_dispatch_s = 0.0
        h = self._phase_h.get("sched")
        if h is None:
            h = self.registry.histogram("engine.phase.sched_s")
            self._phase_h["sched"] = h
        h.observe(max(0.0, (t1 - t0) - nested))
        # the trace span keeps the full wall extent (visual truth: nested
        # prefill spans draw inside it on the engine track)
        self.tracer.engine_span("sched", t0, t1,
                                nested_dispatch_s=round(nested, 6))

    def join_wait(self, t0: float, t1: float):
        """An overlap-mode `_join_dispatch` block (waiting for the async
        dispatch's page binding before a prefill/COW can chain on it):
        recorded as ``overlap_join_sync`` — the ``_sync`` suffix lands it
        in the device-wait bucket — and accumulated into the nested-
        dispatch subtraction so the enclosing ``sched`` span stays pure
        host time (the buckets must remain disjoint)."""
        self._nested_dispatch_s += t1 - t0
        self.phase("overlap_join_sync", t0, t1)

    def bridge_begin(self, name: str):
        """Enter a ``paddle_tpu.profiler.host_annotation`` span (bridge on
        only) around a dispatch the caller times manually; returns the
        entered context (pass it to :meth:`bridge_end`) or None when the
        bridge is off.  The engine brackets its dispatch calls with these
        so host phases land INSIDE any active jax device trace, next to
        the XLA ops they launched."""
        ann = self.tracer.annotation(f"serve.{name}")
        if ann is NULL_CONTEXT:
            return None
        ann.__enter__()
        return ann

    @staticmethod
    def bridge_end(ann):
        if ann is not None:
            ann.__exit__(None, None, None)

    def request_event(self, rid: int, name: str, t: float | None = None,
                      **attrs):
        self.tracer.request_event(rid, name, t=t, **attrs)

    def _dump(self, reason: str, **extra) -> dict:
        self._c_dumps.inc()
        ramp = self.memory.tail(self.mem_ramp_events)
        if ramp:
            # the occupancy ramp that led here — a pool-pressure postmortem
            # needs the trajectory, not just the final free-page count
            extra = dict(extra)
            extra["memory_ramp"] = ramp
        return self.flight.dump(reason, **extra)

    # -- compile accounting ------------------------------------------------
    def compiled(self, name: str, n: int, dur_s: float):
        """One jit compile-cache miss (from the `analysis.sanitize`
        instrumentation wrapping the engine's `_jit()` executables):
        `n` new variants for model fn `name`, costing `dur_s` wall seconds
        (compile + first execution — what the miss cost the caller)."""
        self._c_compiles.inc(n)
        self._h_compile.observe(dur_s)
        e = self._compiles.setdefault(name, {"count": 0, "total_s": 0.0})
        e["count"] += n
        e["total_s"] += dur_s
        self.flight.record("compile", fn=name, variants=n,
                           dur_s=round(dur_s, 6))

    def compile_report(self) -> dict:
        """Cumulative per-fn compile counts/durations (engine lifetime —
        deliberately NOT window-scoped: warm-up compiles are the bulk and
        a timed-window miss shows up in `jit_cache_misses` deltas)."""
        return {
            "total_compiles": self._c_compiles.value,
            "compile_s_total": round(self._h_compile.total, 6),
            "compile_s_max": round(self._h_compile.max, 6)
            if self._h_compile.count else 0.0,
            "per_fn": {k: {"count": v["count"],
                           "total_s": round(v["total_s"], 6)}
                       for k, v in sorted(self._compiles.items())},
        }

    # -- memory observatory ------------------------------------------------
    def _device_bytes(self):
        """Live device-buffer bytes via jax device memory stats, or None
        where the backend exposes none (CPU).  The device handle resolves
        once; an unsupported backend short-circuits forever after."""
        if self._device is False:
            return None
        if self._device is None:
            try:
                import jax
                self._device = jax.local_devices()[0]
            except Exception:
                self._device = False
                return None
        try:
            st = self._device.memory_stats()
        except Exception:
            st = None
        if not st:
            self._device = False
            return None
        return int(st.get("bytes_in_use", 0))

    def sample_memory(self, engine):
        """One memory-observatory row at an engine-step end (host state
        reads only — the pool/cache/queue live on the host, and the jax
        memory-stats call is a runtime query, not a device sync)."""
        t = self.clock()
        pool = engine.pool
        total = pool.num_pages
        free = pool.num_free
        cache = engine.cache
        cache_refs = len(cache) if cache is not None else 0
        slot_pages = 0
        slot_tokens = 0
        for s, slot in enumerate(engine._slots):
            if slot is not None:
                slot_pages += len(slot.pages)
                slot_tokens += int(engine._lengths[s])
        # internal fragmentation: token capacity the live page tables hold
        # but no sequence fills (tail-of-page waste) — pages are fixed-size
        # so this, not external fragmentation, is the waste axis
        frag = 1.0 - slot_tokens / (slot_pages * pool.page_size) \
            if slot_pages else 0.0
        occ = (total - free) / total
        # occupancy in BYTES (pages x page_bytes for the active kv_dtype):
        # a quantized page store's capacity win must be visible in mem.*
        # gauges and fleet snapshots, not just in page counts
        pb = int(getattr(engine, "page_bytes", 0) or 0)
        fields = dict(
            step=engine._step_seq, total_pages=total, free_pages=free,
            allocated_pages=pool.num_allocated,
            referenced=pool.num_referenced, cache_page_refs=cache_refs,
            page_bytes=pb,
            pool_allocated_bytes=pool.num_allocated * pb,
            pool_capacity_bytes=total * pb,
            occupancy_frac=round(occ, 4),
            fragmentation_frac=round(frag, 4), slot_tokens=slot_tokens,
            queue_depth=len(engine._queue), active=engine.num_active,
            # cumulative prefix-cache accounting per row: the health
            # sentinel's hit-rate-collapse rule reads WINDOWED deltas of
            # these (Δhit / Δ(hit+executed)) straight off the series
            cache_hit_tokens=engine.cache_hit_tokens,
            prefill_tokens_executed=engine.prefill_tokens)
        dev = self._device_bytes()
        if dev is not None:
            fields["device_bytes_in_use"] = dev
        self.memory.sample(t, **fields)
        self._g_free.set(free)
        self._g_occ.set(occ)
        self._g_frag.set(frag)
        self._g_cache.set(cache_refs)
        self._g_queue.set(len(engine._queue))
        self._g_alloc_bytes.set(pool.num_allocated * pb)
        self._g_cap_bytes.set(total * pb)
        # Perfetto counter tracks next to the PR 6 request spans
        self.tracer.counter("pagepool.pages", t, used=total - free,
                            free=free, cached=cache_refs)
        self.tracer.counter("engine.load", t, queue_depth=len(engine._queue),
                            active=engine.num_active)

    def memory_report(self, engine_stats: dict | None = None) -> dict:
        """Memory-observatory summary over the retained series (the
        current measurement window after `reset_window()`): last sample,
        occupancy/fragmentation peaks, free-page floor — plus prefix-cache
        hit accounting when the engine's `stats()` dict is passed."""
        rows = self.memory.rows()
        rep = {"samples": len(rows),
               "total_samples": self.memory.total_samples,
               "last": rows[-1] if rows else None}
        for key, field, fn in (("peak_occupancy_frac", "occupancy_frac", max),
                               ("peak_fragmentation_frac",
                                "fragmentation_frac", max),
                               ("min_free_pages", "free_pages", min)):
            mm = self.memory.field_minmax(field)
            rep[key] = (mm[1] if fn is max else mm[0]) if mm else None
        if engine_stats is not None:
            hit = int(engine_stats.get("cached_prefix_tokens", 0))
            run = int(engine_stats.get("prefill_tokens_executed", 0))
            rep["prefix_cache"] = {
                "hit_tokens": hit, "executed_tokens": run,
                "hit_rate": round(hit / (hit + run), 4) if hit + run else 0.0,
                "evictions": int(engine_stats.get("cache_evictions", 0)),
            }
        return rep

    # -- engine lifecycle hooks --------------------------------------------
    def submitted(self, req, queue_depth: int):
        self._c_submitted.inc()
        attrs = dict(prompt_tokens=len(req.prompt),
                     max_new_tokens=req.max_new_tokens)
        if req.generated:
            # a mid-flight adoption (fleet migration / manual adopt): the
            # record starts with tokens already emitted elsewhere — the
            # attribution analyzer reads this to label the residency
            attrs["resumed_tokens"] = len(req.generated)
        if getattr(req, "trace_id", None) is not None:
            # cross-component trace stitching: the trace_id rides the
            # request record so TraceStitcher can bind this engine's span
            # to the frontend/router spans of the same request
            attrs["trace_id"] = req.trace_id
        self.tracer.request_event(req.rid, "submitted", t=req.submit_time,
                                  **attrs)
        self.tracer.request_event(req.rid, "queued", t=req.submit_time,
                                  depth=queue_depth)
        self.flight.record("submit", rid=req.rid,
                           prompt_tokens=len(req.prompt), depth=queue_depth)

    def rejected(self, queue_depth: int, max_queue: int):
        self._c_rejections.inc()
        t = self.clock()
        self.tracer.engine_event("rejected", t=t, depth=queue_depth,
                                 max_queue=max_queue)
        self.flight.record("reject", depth=queue_depth, max_queue=max_queue)

    def admitted(self, req, slot: int, t: float, resuming: bool,
                 first: bool, cached_tokens: int, prefill_tokens: int):
        if first:
            # FIRST admission only: t - submit is the queue wait TTFT used
            # to hide inside first_token_time.  `first` is the engine's
            # admit_time==0 predicate, NOT `not resuming` — a preemption
            # victim evicted before emitting anything re-admits with
            # resuming=False but must not re-observe (inflated) queue wait.
            self._h_queue.observe(t - req.submit_time)
        self.tracer.request_event(req.rid, "admitted", t=t, slot=slot,
                                  resuming=resuming, first=first,
                                  cached_tokens=cached_tokens,
                                  prefill_tokens=prefill_tokens)
        if cached_tokens:
            self.tracer.request_event(req.rid, "cache_hit", t=t,
                                      tokens=cached_tokens)
        self.flight.record("admit", rid=req.rid, slot=slot,
                           resuming=resuming, cached_tokens=cached_tokens,
                           prefill_tokens=prefill_tokens)

    def prefill_dispatch(self, rid: int, pos: int, tokens: int, t0: float,
                         kind: str = "prefill_chunk"):
        """One prefill dispatch's host span (kind: ``prefill_chunk`` for
        the chunked/suffix path, ``prefill_dense`` for the fused
        whole-prompt prefill+sample)."""
        t1 = self.clock()
        self._nested_dispatch_s += t1 - t0
        self._h_prefill_tok.observe(tokens)
        self.phase(kind, t0, t1, rid=rid, tokens=tokens)
        self.tracer.request_event(rid, kind, t=t1, pos=pos,
                                  tokens=tokens, dur=t1 - t0)

    def first_token(self, req):
        t = req.first_token_time
        self._h_ttft.observe(t - req.submit_time)
        if req.admit_time:
            self._h_prefill.observe(t - req.admit_time)
        self.tracer.request_event(req.rid, "first_token", t=t,
                                  ttft_s=t - req.submit_time)

    def cow_copy(self, rid: int, src: int, dst: int):
        self._c_cow.inc()
        self.tracer.request_event(rid, "cow_copy", src=src, dst=dst)
        self.flight.record("cow", rid=rid, src=src, dst=dst)

    def evicted(self, requested: int, freed: int):
        self._c_evictions.inc(freed)
        t = self.clock()
        self.tracer.engine_event("cache_evict", t=t, requested=requested,
                                 freed=freed)
        self.flight.record("evict", requested=requested, freed=freed)

    def preempted(self, req, step: int) -> dict | None:
        """Record a preemption; detect storms (``storm_threshold``
        preemptions within the last ``storm_window`` engine steps) and
        auto-dump once per storm.  Returns the dump when one fired."""
        self._c_preemptions.inc()
        self.tracer.request_event(req.rid, "preempted",
                                  generated=len(req.generated),
                                  preemptions=req.preemptions)
        self.flight.record("preempt", rid=req.rid, step=step,
                           generated=len(req.generated))
        q = self._preempt_steps
        q.append(step)
        while q and q[0] < step - self.storm_window:
            q.popleft()
        if len(q) >= self.storm_threshold \
                and self._storm_dumped_at < step - self.storm_window:
            self._storm_dumped_at = step
            return self._dump("preemption_storm", step=step,
                              preemptions_in_window=len(q),
                              window_steps=self.storm_window)
        return None

    def retired(self, req, reason: str = "retired"):
        t = req.retire_time or self.clock()
        self._c_retired.inc()
        tokens = len(req.generated)
        ttft = (req.first_token_time - req.submit_time) \
            if req.first_token_time else None
        tpot = req.tpot or None
        e2e = t - req.submit_time
        self._h_e2e.observe(e2e)
        if tpot:
            self._h_tpot.observe(tpot)
        if req.timed_out:
            self._c_timed_out.inc()
            self.tracer.request_event(req.rid, "deadline",
                                      generated=tokens)
            self.flight.record("timeout", rid=req.rid, tokens=tokens)
        self.tracer.request_event(req.rid, "retired", t=t, tokens=tokens,
                                  timed_out=req.timed_out,
                                  preemptions=req.preemptions)
        self.flight.record("retire", rid=req.rid, tokens=tokens,
                           timed_out=req.timed_out)
        summary = {
            "rid": req.rid, "tokens": tokens, "ttft_s": ttft,
            "tpot_s": tpot, "e2e_s": e2e,
            "queue_s": req.queue_time or None,
            "timed_out": req.timed_out, "preemptions": req.preemptions,
            "cached_prefix_tokens": req.cached_prefix_tokens,
            # retirement stamp: the burn-rate detector windows on this
            "at": t,
        }
        self.request_summaries.append(summary)
        if self.tail is not None:
            # the record the retired event just completed sits at the top
            # of the done ring — O(1), no linear rid scan
            done = self.tracer._done
            tr = done[-1] if done and done[-1].rid == req.rid \
                else self.tracer.get(req.rid)
            if tr is not None:
                self.tail.offer(summary, tr, self.tracer,
                                context=self.memory.last)

    def cancelled(self, rid: int):
        """A request cancelled mid-flight (client disconnect / zombie
        prune): terminate its trace record — cancels are terminal, and a
        live-table ghost would grow the tracer unboundedly — and flight-
        record the cancellation.  No latency histograms: a cancel is not
        a completion."""
        self.tracer.request_event(rid, "retired", cancelled=True)
        self.flight.record("cancel", rid=rid)

    def step_done(self, engine, t0: float, progressed: bool,
                  tokens: int):
        t1 = self.clock()
        self._h_step.observe(t1 - t0)
        self.tracer.engine_span("step", t0, t1,
                                step=engine._step_seq,
                                progressed=progressed, tokens=tokens)
        # memory observatory sample BEFORE the step/fault records, so a
        # pool-pressure dump's ramp already includes this step's occupancy
        self.sample_memory(engine)
        inflight = getattr(engine, "inflight_depth", 0)
        self._g_inflight.set(inflight)
        self.flight.record("step", step=engine._step_seq,
                           progressed=progressed, tokens=tokens,
                           active=engine.num_active,
                           queued=len(engine._queue),
                           free_pages=engine.pool.num_free,
                           inflight=inflight)
        if engine._pressure:
            self.flight.record("fault", point="serve.pool_pressure",
                               step=engine._step_seq)
            self._dump("injected_fault", point="serve.pool_pressure",
                       step=engine._step_seq)
        if self.sentinel is not None:
            # the health sentinel rides THIS hook (right after the
            # memory-observatory sample, so every rule sees the fresh
            # series row): no new hook sites, zero cost when absent
            self.sentinel.on_step(self)

    def fault_dump(self, reason: str, **extra) -> dict:
        return self._dump(reason, **extra)

    def freeze(self):
        """Freeze the registry (registry-freeze invariant): every metric
        the engine's hot path can touch is pre-registered above, so a
        frozen registry only rejects NEW names created from non-main
        threads — the frontend/fleet/exporter wiring calls this once the
        worker threads are about to start."""
        self.registry.freeze()

    def reset_window(self):
        """Start a fresh measurement window: clear the per-request SLO
        summaries and reset the latency histograms (step/phase/request)
        and the memory series, so `slo_report`, `utilization_report`,
        `memory_report`, and the histogram snapshots describe the window —
        not the warm-up compiles that preceded it.  Counters, the compile
        record, and the tracer/flight record stay cumulative (they are
        event history, not window statistics)."""
        self.request_summaries.clear()
        for h in (self._h_ttft, self._h_tpot, self._h_queue,
                  self._h_prefill, self._h_e2e, self._h_step,
                  self._h_prefill_tok, *self._phase_h.values()):
            h.reset()
        self.memory.reset()
        if self.tail is not None:
            # warm-pass outliers (compile-inflated) must not shadow the
            # measured window's true tail
            self.tail.reset()
        if self.sentinel is not None:
            # rule windows + derived baselines restart with the window;
            # active alerts are force-cleared, so the live gauge must
            # follow (a stale nonzero would contradict /alerts until the
            # next fire/clear event)
            self.sentinel.reset()
            self._g_active_alerts.set(0)

    # -- readouts ----------------------------------------------------------
    def utilization_report(self, window_s: float | None = None) -> dict:
        """Host/device step decomposition over the current measurement
        window — the overlap-headroom readout ROADMAP item 5 is gated on.

        Every engine phase histogram (host timestamps at the EXISTING
        sync boundaries only) lands in one of three buckets:

          * ``host_busy_s`` — pure host scheduling/bookkeeping (``sched``,
            ``*_record``): the device has nothing to run that this engine
            dispatched;
          * ``dispatch_s`` — time inside dispatch calls (``*_dispatch``,
            ``prefill_*``): enqueue cost on an async backend, enqueue +
            execution where dispatch blocks (CPU jax) — the fused
            prefills' execution is inseparable from their dispatch at
            this layer, so it is counted here, honestly over- rather
            than under-stating device busyness;
          * ``device_wait_s`` — host blocked fetching results at the
            annotated sync points (``*_sync``): the only bucket where the
            device is PROVABLY the bottleneck.

        With ``window_s`` (the measured wall clock), ``gap_s`` is the
        unaccounted remainder (inter-step host work, bench bookkeeping)
        and ``device_idle_frac_est`` = (host_busy + gap) / window — the
        fraction of the window the device provably had nothing dispatched
        to run, i.e. the headroom a double-buffered host loop (ROADMAP
        item 5) could reclaim."""
        host = disp = wait = 0.0
        per_phase = {}
        for name in sorted(self._phase_h):
            h = self._phase_h[name]
            per_phase[name] = {"total_s": round(h.total, 6),
                               "count": h.count}
            if name.endswith("_sync"):
                wait += h.total
            elif name.endswith("_dispatch") or name.startswith("prefill"):
                disp += h.total
            else:
                host += h.total
        rep = {"steps": self._h_step.count,
               "step_host_s_total": round(self._h_step.total, 6),
               "host_busy_s": round(host, 6),
               "dispatch_s": round(disp, 6),
               "device_wait_s": round(wait, 6),
               "per_phase": per_phase}
        if window_s is not None and window_s > 0:
            gap = max(0.0, window_s - (host + disp + wait))
            rep["window_s"] = round(float(window_s), 6)
            rep["gap_s"] = round(gap, 6)
            rep["host_busy_frac"] = round(host / window_s, 4)
            rep["dispatch_frac"] = round(disp / window_s, 4)
            rep["device_wait_frac"] = round(wait / window_s, 4)
            rep["gap_frac"] = round(gap / window_s, 4)
            rep["device_idle_frac_est"] = round((host + gap) / window_s, 4)
        return rep

    def snapshot(self, engine_stats: dict | None = None) -> dict:
        """Full metrics snapshot; when the engine's ``stats()`` dict is
        passed, its counters fold in under ``engine.*`` so one artifact
        carries both views."""
        snap = self.registry.snapshot()
        if engine_stats is not None:
            for k, v in engine_stats.items():
                if isinstance(v, dict):
                    for k2, v2 in v.items():
                        snap[f"engine.{k}.{k2}"] = v2
                else:
                    snap[f"engine.{k}"] = v
        return snap

    def slo_report(self, ttft_deadline_s: float,
                   window_s: float | None = None) -> dict:
        """TTFT/TPOT/E2E quantiles + goodput at the deadline, plus the
        engine step-latency quantiles (host)."""
        rep = slo_report(self.request_summaries, ttft_deadline_s,
                         window_s=window_s)
        q = self._h_step.percentiles()
        rep["step_latency"] = {"p50_ms": round(q[50] * 1e3, 3),
                               "p95_ms": round(q[95] * 1e3, 3),
                               "p99_ms": round(q[99] * 1e3, 3),
                               "count": self._h_step.count}
        return rep
