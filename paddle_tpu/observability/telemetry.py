"""Telemetry facade the serving engine threads through its step loop.

One object bundles the three observability pieces (metrics registry,
request-lifecycle tracer, crash flight recorder) behind engine-shaped
methods, so ``inference/paged.py`` stays readable: every hook site in the
engine is one ``if tel is not None:`` flag check — telemetry OFF is a
no-op fast path with zero per-token Python work, telemetry ON records at
existing host-sync boundaries only (no new device round-trips; graftlint
SYNC001 stays clean and the jit variant counts are untouched — telemetry
is pure host code).

Metric catalog (README §Observability):

  histograms (seconds): ``serve.ttft_s``, ``serve.tpot_s``,
    ``serve.queue_s``, ``serve.prefill_s``, ``serve.e2e_s``,
    ``engine.step_host_s``, ``engine.phase.<name>_s`` for phases
    ``sched`` (retire+admit host work), ``prefill_chunk``,
    ``decode_dispatch`` / ``decode_sync`` / ``decode_record``,
    ``verify_dispatch`` / ``verify_sync`` / ``verify_record``
  counters: ``serve.requests_submitted``, ``serve.requests_retired``,
    ``serve.requests_timed_out``, ``serve.rejections``,
    ``serve.preemptions``, ``serve.cache_evictions``, ``serve.cow_copies``,
    ``serve.flight_dumps``

Flight-recorder event ladder (the degradation-ladder events land in the
ring in the order the engine walks the rungs): ``submit`` -> ``admit`` ->
``evict`` -> ``preempt`` (+ ``reject``, ``timeout``, ``fault``, ``step``,
``retire``, ``cow``).  Dumps fire automatically on ``EngineStalledError``,
``RecompileBudgetError``, preemption storms (``storm_threshold``
preemptions within ``storm_window`` engine steps), and injected faults."""
from __future__ import annotations

import time
from collections import deque

from .flight import FlightRecorder
from .metrics import MetricsRegistry
from .slo import slo_report
from .tracing import NULL_CONTEXT, Tracer

__all__ = ["Telemetry"]


class Telemetry:
    """Serving-engine telemetry: pass ``telemetry=Telemetry(...)`` (or
    ``telemetry=True`` for defaults) to :class:`ServingEngine`.

    ``clock`` is injectable for deterministic tests and is shared by the
    registry, tracer, and flight recorder, so one fake clock drives every
    timestamp.  ``profiler_bridge=True`` additionally wraps engine
    dispatch phases in ``paddle_tpu.profiler`` annotations."""

    def __init__(self, clock=time.perf_counter, flight_capacity: int = 256,
                 flight_dump_path: str | None = None,
                 storm_threshold: int = 4, storm_window: int = 32,
                 profiler_bridge: bool = False, max_completed: int = 4096):
        self.clock = clock
        self.registry = MetricsRegistry(clock=clock)
        self.tracer = Tracer(clock=clock, bridge=profiler_bridge,
                             max_completed=max_completed)
        self.flight = FlightRecorder(capacity=flight_capacity, clock=clock,
                                     dump_path=flight_dump_path)
        self.storm_threshold = int(storm_threshold)
        self.storm_window = int(storm_window)
        self._preempt_steps: deque[int] = deque()
        self._storm_dumped_at = -(1 << 60)   # "never" (one dump per storm)
        # per-request summaries for exact SLO/goodput accounting (bounded)
        self.request_summaries: deque[dict] = deque(maxlen=max_completed)
        r = self.registry
        self._h_ttft = r.histogram("serve.ttft_s")
        self._h_tpot = r.histogram("serve.tpot_s")
        self._h_queue = r.histogram("serve.queue_s")
        self._h_prefill = r.histogram("serve.prefill_s")
        self._h_e2e = r.histogram("serve.e2e_s")
        self._h_step = r.histogram("engine.step_host_s")
        self._phase_h = {}
        self._c_submitted = r.counter("serve.requests_submitted")
        self._c_retired = r.counter("serve.requests_retired")
        self._c_timed_out = r.counter("serve.requests_timed_out")
        self._c_rejections = r.counter("serve.rejections")
        self._c_preemptions = r.counter("serve.preemptions")
        self._c_evictions = r.counter("serve.cache_evictions")
        self._c_cow = r.counter("serve.cow_copies")
        self._c_dumps = r.counter("serve.flight_dumps")

    # -- low-level ---------------------------------------------------------
    def phase(self, name: str, t0: float, t1: float, **attrs):
        h = self._phase_h.get(name)
        if h is None:
            h = self.registry.histogram(f"engine.phase.{name}_s")
            self._phase_h[name] = h
        h.observe(t1 - t0)
        self.tracer.engine_span(name, t0, t1, **attrs)

    def bridge_begin(self, name: str):
        """Enter a ``paddle_tpu.profiler.host_annotation`` span (bridge on
        only) around a dispatch the caller times manually; returns the
        entered context (pass it to :meth:`bridge_end`) or None when the
        bridge is off.  The engine brackets its dispatch calls with these
        so host phases land INSIDE any active jax device trace, next to
        the XLA ops they launched."""
        ann = self.tracer.annotation(f"serve.{name}")
        if ann is NULL_CONTEXT:
            return None
        ann.__enter__()
        return ann

    @staticmethod
    def bridge_end(ann):
        if ann is not None:
            ann.__exit__(None, None, None)

    def request_event(self, rid: int, name: str, t: float | None = None,
                      **attrs):
        self.tracer.request_event(rid, name, t=t, **attrs)

    def _dump(self, reason: str, **extra) -> dict:
        self._c_dumps.inc()
        return self.flight.dump(reason, **extra)

    # -- engine lifecycle hooks --------------------------------------------
    def submitted(self, req, queue_depth: int):
        self._c_submitted.inc()
        self.tracer.request_event(req.rid, "submitted", t=req.submit_time,
                                  prompt_tokens=len(req.prompt),
                                  max_new_tokens=req.max_new_tokens)
        self.tracer.request_event(req.rid, "queued", t=req.submit_time,
                                  depth=queue_depth)
        self.flight.record("submit", rid=req.rid,
                           prompt_tokens=len(req.prompt), depth=queue_depth)

    def rejected(self, queue_depth: int, max_queue: int):
        self._c_rejections.inc()
        t = self.clock()
        self.tracer.engine_event("rejected", t=t, depth=queue_depth,
                                 max_queue=max_queue)
        self.flight.record("reject", depth=queue_depth, max_queue=max_queue)

    def admitted(self, req, slot: int, t: float, resuming: bool,
                 first: bool, cached_tokens: int, prefill_tokens: int):
        if first:
            # FIRST admission only: t - submit is the queue wait TTFT used
            # to hide inside first_token_time.  `first` is the engine's
            # admit_time==0 predicate, NOT `not resuming` — a preemption
            # victim evicted before emitting anything re-admits with
            # resuming=False but must not re-observe (inflated) queue wait.
            self._h_queue.observe(t - req.submit_time)
        self.tracer.request_event(req.rid, "admitted", t=t, slot=slot,
                                  resuming=resuming, first=first,
                                  cached_tokens=cached_tokens,
                                  prefill_tokens=prefill_tokens)
        if cached_tokens:
            self.tracer.request_event(req.rid, "cache_hit", t=t,
                                      tokens=cached_tokens)
        self.flight.record("admit", rid=req.rid, slot=slot,
                           resuming=resuming, cached_tokens=cached_tokens,
                           prefill_tokens=prefill_tokens)

    def prefill_dispatch(self, rid: int, pos: int, tokens: int, t0: float,
                         kind: str = "prefill_chunk"):
        """One prefill dispatch's host span (kind: ``prefill_chunk`` for
        the chunked/suffix path, ``prefill_dense`` for the fused
        whole-prompt prefill+sample)."""
        t1 = self.clock()
        self.phase(kind, t0, t1, rid=rid, tokens=tokens)
        self.tracer.request_event(rid, kind, t=t1, pos=pos,
                                  tokens=tokens, dur=t1 - t0)

    def first_token(self, req):
        t = req.first_token_time
        self._h_ttft.observe(t - req.submit_time)
        if req.admit_time:
            self._h_prefill.observe(t - req.admit_time)
        self.tracer.request_event(req.rid, "first_token", t=t,
                                  ttft_s=t - req.submit_time)

    def cow_copy(self, rid: int, src: int, dst: int):
        self._c_cow.inc()
        self.tracer.request_event(rid, "cow_copy", src=src, dst=dst)
        self.flight.record("cow", rid=rid, src=src, dst=dst)

    def evicted(self, requested: int, freed: int):
        self._c_evictions.inc(freed)
        t = self.clock()
        self.tracer.engine_event("cache_evict", t=t, requested=requested,
                                 freed=freed)
        self.flight.record("evict", requested=requested, freed=freed)

    def preempted(self, req, step: int) -> dict | None:
        """Record a preemption; detect storms (``storm_threshold``
        preemptions within the last ``storm_window`` engine steps) and
        auto-dump once per storm.  Returns the dump when one fired."""
        self._c_preemptions.inc()
        self.tracer.request_event(req.rid, "preempted",
                                  generated=len(req.generated),
                                  preemptions=req.preemptions)
        self.flight.record("preempt", rid=req.rid, step=step,
                           generated=len(req.generated))
        q = self._preempt_steps
        q.append(step)
        while q and q[0] < step - self.storm_window:
            q.popleft()
        if len(q) >= self.storm_threshold \
                and self._storm_dumped_at < step - self.storm_window:
            self._storm_dumped_at = step
            return self._dump("preemption_storm", step=step,
                              preemptions_in_window=len(q),
                              window_steps=self.storm_window)
        return None

    def retired(self, req, reason: str = "retired"):
        t = req.retire_time or self.clock()
        self._c_retired.inc()
        tokens = len(req.generated)
        ttft = (req.first_token_time - req.submit_time) \
            if req.first_token_time else None
        tpot = req.tpot or None
        e2e = t - req.submit_time
        self._h_e2e.observe(e2e)
        if tpot:
            self._h_tpot.observe(tpot)
        if req.timed_out:
            self._c_timed_out.inc()
            self.tracer.request_event(req.rid, "deadline",
                                      generated=tokens)
            self.flight.record("timeout", rid=req.rid, tokens=tokens)
        self.tracer.request_event(req.rid, "retired", t=t, tokens=tokens,
                                  timed_out=req.timed_out,
                                  preemptions=req.preemptions)
        self.flight.record("retire", rid=req.rid, tokens=tokens,
                           timed_out=req.timed_out)
        self.request_summaries.append({
            "rid": req.rid, "tokens": tokens, "ttft_s": ttft,
            "tpot_s": tpot, "e2e_s": e2e,
            "queue_s": req.queue_time or None,
            "timed_out": req.timed_out, "preemptions": req.preemptions,
            "cached_prefix_tokens": req.cached_prefix_tokens,
        })

    def step_done(self, engine, t0: float, progressed: bool,
                  tokens: int):
        t1 = self.clock()
        self._h_step.observe(t1 - t0)
        self.tracer.engine_span("step", t0, t1,
                                step=engine._step_seq,
                                progressed=progressed, tokens=tokens)
        self.flight.record("step", step=engine._step_seq,
                           progressed=progressed, tokens=tokens,
                           active=engine.num_active,
                           queued=len(engine._queue),
                           free_pages=engine.pool.num_free)
        if engine._pressure:
            self.flight.record("fault", point="serve.pool_pressure",
                               step=engine._step_seq)
            self._dump("injected_fault", point="serve.pool_pressure",
                       step=engine._step_seq)

    def fault_dump(self, reason: str, **extra) -> dict:
        return self._dump(reason, **extra)

    def reset_window(self):
        """Start a fresh measurement window: clear the per-request SLO
        summaries and reset the latency histograms (step/phase/request),
        so `slo_report` and the histogram snapshots describe the window —
        not the warm-up compiles that preceded it.  Counters and the
        tracer/flight record stay cumulative (they are event history, not
        window statistics)."""
        self.request_summaries.clear()
        for h in (self._h_ttft, self._h_tpot, self._h_queue,
                  self._h_prefill, self._h_e2e, self._h_step,
                  *self._phase_h.values()):
            h.reset()

    # -- readouts ----------------------------------------------------------
    def snapshot(self, engine_stats: dict | None = None) -> dict:
        """Full metrics snapshot; when the engine's ``stats()`` dict is
        passed, its counters fold in under ``engine.*`` so one artifact
        carries both views."""
        snap = self.registry.snapshot()
        if engine_stats is not None:
            for k, v in engine_stats.items():
                if isinstance(v, dict):
                    for k2, v2 in v.items():
                        snap[f"engine.{k}.{k2}"] = v2
                else:
                    snap[f"engine.{k}"] = v
        return snap

    def slo_report(self, ttft_deadline_s: float,
                   window_s: float | None = None) -> dict:
        """TTFT/TPOT/E2E quantiles + goodput at the deadline, plus the
        engine step-latency quantiles (host)."""
        rep = slo_report(self.request_summaries, ttft_deadline_s,
                         window_s=window_s)
        q = self._h_step.percentiles()
        rep["step_latency"] = {"p50_ms": round(q[50] * 1e3, 3),
                               "p95_ms": round(q[95] * 1e3, 3),
                               "p99_ms": round(q[99] * 1e3, 3),
                               "count": self._h_step.count}
        return rep
