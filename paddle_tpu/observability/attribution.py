"""Per-request critical-path attribution + tail-outlier capture (ISSUE 13
tentpole, part a).

PRs 6-7 and 12 answer "what are the quantiles" — nothing in the repo
answers **"why was THIS p99 request slow"**.  This module decomposes each
request's end-to-end latency into EXACT DISJOINT segments over its traced
lifetime, so a slow request reads as a bill of materials instead of a
single number:

    queue 41%  |  admission 3%  |  prefill_chunk 22%  |  decode_sync 30%
    decode_dispatch 2%  |  decode_record 1%  |  host_other 1%

The decomposition overlays the engine-scope phase spans (the PR 6/7
``Tracer`` engine track: ``sched``, ``prefill_*``, ``decode_*``,
``verify_*``, ``overlap_*``) onto the request's own lifecycle window
(``submitted`` .. ``retired``): at every instant of the request's life,
the segment is *what the engine was doing* — waiting in queue, host
scheduling (``admission``), dispatching or syncing a decode, verifying
drafts.  Segments are built on shared boundary floats, so they are
contiguous and disjoint BY CONSTRUCTION and their durations telescope to
the traced e2e (:meth:`CriticalPath.is_exact` asserts the structure;
``exact_requests == requests`` is a ``perf/check_obs.py`` gate).

Cross-replica requests (failover, live migration, snapshot restore)
attribute through the stitched view (:func:`attribute_stitched`): the
component tracers a ``trace_id`` crossed are ordered by first touch, each
engine residency attributes locally, and the inter-engine gaps classify
as ``migration`` (adopt / re-prefill placement) or ``snapshot_restore``
(the successor record carries ``restored=True``).

Tail forensics: :class:`TailRecorder` auto-captures the top-K slowest
requests at retirement — full span chain, computed attribution, and the
engine-state context row (pool occupancy / queue depth at the time) — as
flight-style outlier dumps, browsable live via the exporter's ``/slow``
endpoint.  Capture is O(log K) per retirement (a heap check); the
attribution itself is only computed for requests that enter the top K.

Everything here is pure host code over already-recorded traces: zero jit
calls, zero device syncs, zero per-token work.
"""
from __future__ import annotations

import heapq
import math
import time
from bisect import bisect_left, bisect_right

__all__ = ["SEGMENT_KINDS", "CriticalPath", "attribute", "attribute_trace",
           "attribute_stitched", "attribution_report",
           "stitched_attribution_report", "TailRecorder",
           "merge_tail_dumps"]

# engine-span name -> attribution segment kind.  The overlap_* phases are
# the double-buffered loop's decode phases (same roles, suffix convention
# shared with Telemetry.utilization_report); verify_* collapse into one
# ``verify`` segment (dispatch/sync/record of a speculative verify are one
# causal unit from the request's point of view).
_SPAN_KIND = {
    "sched": "admission",
    "prefill_dense": "prefill_dense",
    "prefill_chunk": "prefill_chunk",
    "decode_dispatch": "decode_dispatch",
    "overlap_dispatch": "decode_dispatch",
    "decode_sync": "decode_sync",
    "overlap_sync": "decode_sync",
    "overlap_join_sync": "decode_sync",
    "decode_record": "decode_record",
    "overlap_record": "decode_record",
    "verify_dispatch": "verify",
    "verify_sync": "verify",
    "verify_record": "verify",
}

SEGMENT_KINDS = ("queue", "admission", "prefill_dense", "prefill_chunk",
                 "decode_dispatch", "decode_sync", "decode_record", "verify",
                 "migration", "snapshot_restore", "kv_transfer", "host_other")


class CriticalPath:
    """One request's exact latency decomposition.

    ``segments`` is an ordered list of ``(kind, t0, t1, component)`` tuples
    sharing boundary floats: ``segments[i][2] is segments[i+1][1]`` up to
    float identity, the first starts at the traced window's start and the
    last ends at its end — disjointness and exact coverage are structural,
    not numerical, properties (:meth:`is_exact`)."""

    __slots__ = ("key", "trace_id", "t0", "t1", "segments")

    def __init__(self, key, trace_id, t0: float, t1: float, segments):
        self.key = key                  # rid (single engine) or trace_id
        self.trace_id = trace_id
        self.t0 = float(t0)
        self.t1 = float(t1)
        self.segments = list(segments)

    @property
    def traced_e2e_s(self) -> float:
        """e2e as the tracer saw it: last event minus first event."""
        return self.t1 - self.t0

    @property
    def e2e_s(self) -> float:
        """e2e as the segments bill it (math.fsum of durations)."""
        return math.fsum(t1 - t0 for _k, t0, t1, _c in self.segments)

    def is_exact(self) -> bool:
        """Structural exactness: contiguous non-negative segments covering
        [t0, t1] with no gaps and no overlaps."""
        if not self.segments:
            return self.t1 == self.t0
        if self.segments[0][1] != self.t0 or self.segments[-1][2] != self.t1:
            return False
        prev_end = self.t0
        for _k, a, b, _c in self.segments:
            if a != prev_end or b < a:
                return False
            prev_end = b
        return True

    def sum_matches(self, rel_tol: float = 1e-9) -> bool:
        """The telescoped duration sum equals the traced e2e (float
        rounding of the pairwise differences is the only slack)."""
        ref = abs(self.traced_e2e_s)
        return abs(self.e2e_s - self.traced_e2e_s) <= rel_tol * max(1.0, ref)

    def totals(self) -> dict:
        """{kind: seconds} over the segments (fsum per kind)."""
        acc: dict[str, list] = {}
        for kind, a, b, _c in self.segments:
            acc.setdefault(kind, []).append(b - a)
        return {k: math.fsum(v) for k, v in sorted(acc.items())}

    def fractions(self) -> dict:
        e2e = self.traced_e2e_s
        if e2e <= 0.0:
            return {k: 0.0 for k in self.totals()}
        return {k: v / e2e for k, v in self.totals().items()}

    def to_dict(self, segments: bool = False) -> dict:
        out = {
            "key": self.key,
            "trace_id": self.trace_id,
            "e2e_s": round(self.traced_e2e_s, 9),
            "exact": self.is_exact() and self.sum_matches(),
            "totals_s": {k: round(v, 9) for k, v in self.totals().items()},
            "fractions": {k: round(v, 4)
                          for k, v in self.fractions().items()},
        }
        if segments:
            out["segments"] = [
                {"kind": k, "t0": round(a, 9), "t1": round(b, 9),
                 "component": c} for k, a, b, c in self.segments]
        return out


# ---------------------------------------------------------------------------
# single-tracer attribution
# ---------------------------------------------------------------------------
def _engine_spans(tracer) -> tuple[list, list, list]:
    """Sorted (t0, t1, name) phase spans from a tracer's engine track,
    plus the parallel t0 list for bisect windowing and a prefix-max of
    span ENDS (pmax[i] = max t1 over spans[:i]) so the window scan can
    walk back past short nested spans to a long enclosing one.  Instants
    and non-phase spans (``step``) are ignored."""
    spans = [(t0, t1, name) for (name, t0, t1, _a) in tracer._engine
             if t1 is not None and name in _SPAN_KIND]
    spans.sort()
    pmax = [float("-inf")]
    for _t0, t1, _n in spans:
        pmax.append(max(pmax[-1], t1))
    return spans, [s[0] for s in spans], pmax


def _queue_intervals(events) -> list:
    """[(a, b)] windows where the request sat in the admission queue:
    submitted -> first admitted, and preempted -> re-admitted (a migrated
    record's fresh ``submitted`` re-opens it too)."""
    out = []
    open_t = None
    for name, t, _attrs in events:
        if name in ("submitted", "preempted") and open_t is None:
            open_t = t
        elif name == "admitted" and open_t is not None:
            out.append((open_t, t))
            open_t = None
    if open_t is not None and events:
        out.append((open_t, events[-1][1]))
    return out


def _in_any(t: float, intervals) -> bool:
    return any(a <= t <= b for a, b in intervals)


def _window_segments(events, spans, span_t0s, span_pmax, w_lo: float,
                     w_hi: float, component: str) -> list:
    """Exact segment list for one component residency [w_lo, w_hi]:
    overlay the engine phase spans (innermost wins where they nest — a
    prefill dispatch drawn inside its ``sched`` window bills as prefill),
    default uncovered time to ``queue`` (inside a queue interval) or
    ``host_other``."""
    if w_hi <= w_lo:
        return []
    # candidate spans overlapping the window, clipped to it
    lo_i = bisect_left(span_t0s, w_lo)
    # spans starting before w_lo can still reach into the window — walk
    # back while ANY earlier span does (the prefix-max of ends, not the
    # immediately preceding span: a short nested span sitting between
    # must not hide a long enclosing one that still covers the window)
    i = lo_i
    while i > 0 and span_pmax[i] > w_lo:
        i -= 1
    cand = []
    for t0, t1, name in spans[i:bisect_right(span_t0s, w_hi)]:
        if t1 <= w_lo or t0 >= w_hi:
            continue
        cand.append((max(t0, w_lo), min(t1, w_hi), name))
    queue_iv = [(max(a, w_lo), min(b, w_hi))
                for a, b in _queue_intervals(events)
                if b > w_lo and a < w_hi]
    cuts = {w_lo, w_hi}
    for a, b, _n in cand:
        cuts.add(a)
        cuts.add(b)
    for a, b in queue_iv:
        cuts.add(a)
        cuts.add(b)
    bounds = sorted(cuts)
    segments = []
    for a, b in zip(bounds, bounds[1:]):
        if b <= a:
            continue
        mid = a + (b - a) / 2.0
        if _in_any(mid, queue_iv):
            # queue wait takes PRIORITY over the span overlay: while this
            # request waited for a slot the engine was busy with OTHERS —
            # billing that time as their decode phases would dilute the
            # one number admission/autoscaling policies act on
            kind = "queue"
        else:
            # innermost covering span: max t0, then smallest extent
            # (spans either fully cover an atomic interval or miss it —
            # every span boundary is a cut point)
            best = None
            for t0, t1, name in cand:
                if t0 <= mid <= t1:
                    if best is None or \
                            (t0, -(t1 - t0)) > (best[0],
                                                -(best[1] - best[0])):
                        best = (t0, t1, name)
            kind = _SPAN_KIND[best[2]] if best is not None else "host_other"
        if segments and segments[-1][0] == kind:
            segments[-1] = (kind, segments[-1][1], b, component)
        else:
            segments.append((kind, a, b, component))
    return segments


def _tracer_of(source):
    tracer = getattr(source, "tracer", source)
    if not hasattr(tracer, "_engine"):
        raise TypeError(f"attribute() needs a Tracer or a Telemetry, "
                        f"not {type(source).__name__}")
    return tracer


def _trace_id_of(tr):
    for _name, _t, attrs in tr.events:
        if attrs and "trace_id" in attrs:
            return attrs["trace_id"]
    return None


def attribute_trace(trace, tracer, component: str = "engine") -> CriticalPath:
    """Attribution for one already-located RequestTrace."""
    spans, span_t0s, pmax = _engine_spans(tracer)
    t_lo = trace.events[0][1]
    t_hi = trace.events[-1][1]
    segs = _window_segments(trace.events, spans, span_t0s, pmax, t_lo, t_hi,
                            component)
    return CriticalPath(trace.rid, _trace_id_of(trace), t_lo, t_hi, segs)


def attribute(source, rid: int, component: str = "engine") -> CriticalPath:
    """Critical-path attribution for request ``rid`` on one engine's
    tracer (``source``: a ``Tracer`` or a ``Telemetry``).  Raises KeyError
    for an unknown rid."""
    tracer = _tracer_of(source)
    trace = tracer.get(rid)
    if trace is None or not trace.events:
        raise KeyError(f"no trace recorded for rid {rid}")
    return attribute_trace(trace, tracer, component=component)


# ---------------------------------------------------------------------------
# stitched (cross-component) attribution
# ---------------------------------------------------------------------------
def _is_engine_tracer(tracer) -> bool:
    """A component is an ENGINE residency when its tracer carries real
    phase spans (router/frontend tracers only record request events and
    instants)."""
    return any(t1 is not None and name in _SPAN_KIND
               for name, t0, t1, _a in tracer._engine)


def attribute_stitched(components, trace_id: int) -> CriticalPath | None:
    """Attribution for one end-to-end ``trace_id`` across stitched
    component tracers (``components``: iterable of ``(name, tracer)`` —
    ``ReplicaFleet.trace_components()`` / ``TraceStitcher`` order).

    The request's global window spans from its FIRST touch on any
    component to its LAST.  Engine residencies attribute locally (the
    component's own phase spans); the gap before the first residency is
    ``queue`` (router/frontend placement), a gap BETWEEN residencies is
    ``kv_transfer`` when the successor record was opened by
    ``ServingEngine.import_kv()`` (``handoff=True`` — the disaggregated
    prefill->decode page transfer), ``snapshot_restore`` when re-recorded
    by ``ServingEngine.restore()`` (``restored=True``), and ``migration``
    otherwise (adopt / re-prefill placement), and the tail after the last
    residency (the router heartbeat observing the retirement) is
    ``host_other``.  Returns None when no component saw the trace_id."""
    touches = []
    for name, tracer in components:
        spans, span_t0s, pmax = _engine_spans(tracer)
        is_engine = bool(spans) or _is_engine_tracer(tracer)
        for tr in tracer.traces():
            if not tr.events or _trace_id_of(tr) != trace_id:
                continue
            touches.append({
                "name": name, "tracer": tracer, "trace": tr,
                "spans": spans, "span_t0s": span_t0s, "pmax": pmax,
                "t0": tr.events[0][1], "t1": tr.events[-1][1],
                "engine": is_engine,
                "restored": bool((tr.events[0][2] or {}).get("restored")),
                "handoff": bool((tr.events[0][2] or {}).get("handoff")),
            })
    if not touches:
        return None
    t_lo = min(t["t0"] for t in touches)
    t_hi = max(t["t1"] for t in touches)
    def _retired(t, cancelled):
        last = t["trace"].events[-1]
        return last[0] == "retired" \
            and bool((last[2] or {}).get("cancelled")) == cancelled

    done_ts = [t["t1"] for t in touches if _retired(t, False)] \
        or [t["t1"] for t in touches if _retired(t, True)]
    if done_ts:
        # clamp at the LATEST REAL retirement: a snapshot-restored ZOMBIE
        # copy of an already-resolved request (pruned via cancel by the
        # router) must not re-open the request's window — cancelled
        # records only set the bound when no real retirement exists
        t_hi = max(done_ts)
        touches = [t for t in touches if t["t0"] <= t_hi]
        for t in touches:
            t["t1"] = min(t["t1"], t_hi)
    engines = sorted((t for t in touches if t["engine"]),
                     key=lambda t: (t["t0"], t["t1"]))
    segments: list = []
    cursor = t_lo
    for i, tc in enumerate(engines):
        w_lo = max(tc["t0"], cursor)
        w_hi = max(tc["t1"], w_lo)
        if w_lo > cursor:
            if i == 0:
                kind = "queue"
            elif tc["handoff"]:
                kind = "kv_transfer"
            else:
                kind = "snapshot_restore" if tc["restored"] else "migration"
            segments.append((kind, cursor, w_lo, "fleet"))
        segments.extend(_window_segments(tc["trace"].events, tc["spans"],
                                         tc["span_t0s"], tc["pmax"],
                                         w_lo, w_hi, tc["name"]))
        cursor = max(cursor, w_hi)
    if cursor < t_hi:
        segments.append(("host_other" if engines else "queue",
                         cursor, t_hi, "fleet"))
    return CriticalPath(trace_id, trace_id, t_lo, t_hi, segments)


# ---------------------------------------------------------------------------
# aggregate reports
# ---------------------------------------------------------------------------
def _aggregate(paths, top_k: int) -> dict:
    paths = [p for p in paths if p is not None]
    totals: dict[str, list] = {}
    e2e_all: list[float] = []
    exact = 0
    for p in paths:
        for k, v in p.totals().items():
            totals.setdefault(k, []).append(v)
        e2e_all.append(p.traced_e2e_s)
        if p.is_exact() and p.sum_matches():
            exact += 1
    e2e_total = math.fsum(e2e_all)
    seg = {}
    for k in sorted(totals):
        tot = math.fsum(totals[k])
        seg[k] = {"total_s": round(tot, 6),
                  "frac": round(tot / e2e_total, 4) if e2e_total else 0.0}
    slowest = sorted(paths, key=lambda p: -p.traced_e2e_s)[:top_k]
    return {
        "requests": len(paths),
        "exact_requests": exact,
        "e2e_s_total": round(e2e_total, 6),
        "segments": seg,
        # the headline share: decode_sync is the only bucket where the
        # DEVICE is provably the request's bottleneck (ROADMAP items 1/2
        # need exactly this number to prove where the collective/dequant
        # tax lands)
        "decode_sync_frac": seg.get("decode_sync", {}).get("frac", 0.0),
        "slowest": [p.to_dict() for p in slowest],
    }


def attribution_report(source, top_k: int = 5,
                       component: str = "engine") -> dict:
    """Aggregate attribution over every COMPLETED request on one engine's
    tracer: per-segment totals + e2e shares, exactness census, and the
    top-K slowest requests with their full decomposition."""
    tracer = _tracer_of(source)
    paths = [attribute_trace(tr, tracer, component=component)
             for tr in tracer.traces()
             if tr.events and tr.events[-1][0] == "retired"]
    return _aggregate(paths, top_k)


def stitched_attribution_report(components, top_k: int = 5) -> dict:
    """Aggregate attribution over every stitched end-to-end request
    (``components`` as for :func:`attribute_stitched`): one
    :class:`CriticalPath` per trace_id whose chain saw a retirement."""
    components = list(components)
    done_ids = set()
    for _name, tracer in components:
        for tr in tracer.traces():
            if tr.events and tr.events[-1][0] == "retired":
                tid = _trace_id_of(tr)
                if tid is not None:
                    done_ids.add(tid)
    paths = [attribute_stitched(components, tid) for tid in sorted(done_ids)]
    return _aggregate(paths, top_k)


# ---------------------------------------------------------------------------
# tail-outlier capture
# ---------------------------------------------------------------------------
class TailRecorder:
    """Top-K slowest-request capture (flight-style outlier dumps).

    ``offer()`` is called once per retirement (Telemetry wires it); a
    request slower than the current K-th slowest is captured WITH its
    full span chain, computed attribution, and the engine-state context
    row — the postmortem evidence survives the tracer's bounded completed
    ring.  Browsable live via the exporter ``/slow`` endpoint."""

    def __init__(self, k: int = 8, clock=time.perf_counter):
        if k < 1:
            raise ValueError("TailRecorder k must be >= 1")
        self.k = int(k)
        self.clock = clock
        self.offered = 0
        self._seq = 0
        self._heap: list = []       # (e2e_s, seq, dump) min-heap

    def __len__(self):
        return len(self._heap)

    def offer(self, summary: dict, trace, tracer,
              context: dict | None = None) -> dict | None:
        """Consider one retired request (its Telemetry summary dict, its
        RequestTrace, and the tracer holding the engine spans).  Returns
        the dump when captured, None when the request was fast enough to
        skip (the common case — one float compare)."""
        e2e = summary.get("e2e_s")
        if e2e is None:
            return None
        e2e = float(e2e)
        self.offered += 1
        if len(self._heap) >= self.k and e2e <= self._heap[0][0]:
            return None
        cp = attribute_trace(trace, tracer)
        dump = {
            "reason": "slow_request",
            "rid": trace.rid,
            "trace_id": cp.trace_id,
            "captured_at": float(self.clock()),
            "e2e_s": round(e2e, 9),
            "summary": dict(summary),
            "attribution": cp.to_dict(segments=True),
            "events": [dict({"event": name, "t": round(t, 9)},
                            **(attrs or {}))
                       for name, t, attrs in trace.events],
            "context": dict(context) if context else None,
        }
        self._seq += 1
        heapq.heappush(self._heap, (e2e, self._seq, dump))
        if len(self._heap) > self.k:
            heapq.heappop(self._heap)
        return dump

    def dumps(self) -> list[dict]:
        """Captured outliers, slowest first."""
        return [d for _e, _s, d in
                sorted(self._heap, key=lambda x: (-x[0], x[1]))]

    def reset(self):
        """Window boundary: drop captures (warm-pass outliers must not
        shadow the measured window's tail)."""
        self._heap.clear()
        self.offered = 0

    def report(self) -> dict:
        ds = self.dumps()
        return {
            "k": self.k,
            "captured": len(ds),
            "offered": self.offered,
            "slowest_e2e_s": ds[0]["e2e_s"] if ds else 0.0,
            "rids": [d["rid"] for d in ds],
        }


def merge_tail_dumps(recorders, k: int = 8) -> list[dict]:
    """Fleet-level /slow view: merge per-replica TailRecorder captures
    into one slowest-first top-K list (``recorders``: iterable of
    ``(label, TailRecorder)``)."""
    rows = []
    for label, rec in recorders:
        for d in rec.dumps():
            d = dict(d)
            d["component"] = label
            rows.append(d)
    rows.sort(key=lambda d: -d["e2e_s"])
    return rows[:k]
