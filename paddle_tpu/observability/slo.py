"""SLO reporting: latency quantiles + goodput at a deadline.

One shared implementation for every consumer (bench.py's three serving
traces, ``make obs-check``, dashboards): given per-request summaries from
:class:`~paddle_tpu.observability.telemetry.Telemetry` (or raw latency
lists), produce TTFT/TPOT/E2E quantiles and **goodput** — the share of
work that met its deadline, the number a latency SLO actually pays on.

Goodput here is TTFT-deadline goodput: a request is "good" when its first
token arrived within ``ttft_deadline_s`` of submission (and it was not
retired overdue).  ``goodput_tokens`` counts only good requests' generated
tokens, so ``goodput_tokens_per_sec`` (when a wall-clock window is given)
is directly comparable to raw tokens/s — the gap between the two is the
throughput the SLO would forfeit."""
from __future__ import annotations

from .metrics import Histogram

__all__ = ["latency_percentiles", "slo_report", "on_time", "burn_rate",
           "windowed_burn"]


def on_time(summary: dict, ttft_deadline_s: float) -> bool:
    """THE goodput predicate, shared by :func:`slo_report` and the health
    sentinel's burn-rate detector (one definition of "good", everywhere):
    the request was not retired overdue and its first token arrived
    within the deadline."""
    return (not summary.get("timed_out")
            and summary.get("ttft_s") is not None
            and summary["ttft_s"] <= ttft_deadline_s)


def burn_rate(bad_fraction: float, slo_target: float) -> float:
    """SLO burn rate: the error budget's consumption speed.  With a
    target of ``slo_target`` (e.g. 0.95 of requests on time), the budget
    is ``1 - slo_target``; a ``bad_fraction`` equal to the budget burns
    at exactly 1.0 (on pace), 4x the budget burns at 4.0 (the classic
    page-worthy burn)."""
    budget = max(1e-9, 1.0 - float(slo_target))
    return float(bad_fraction) / budget


def windowed_burn(summaries, ttft_deadline_s: float, *, slo_target: float,
                  window_s: float, now: float) -> dict:
    """Budget consumption over ONE trailing window: request summaries
    (``Telemetry.request_summaries`` — each stamped with its retirement
    time under ``at``, and therefore ASCENDING in ``at``; pass anything
    else pre-sorted) newer than ``now - window_s`` score through
    :func:`on_time`; returns the bad fraction and its burn rate.  The
    health sentinel's fast/slow dual-window TTFT detector calls this
    twice — same math, two windows, zero duplication."""
    lo = now - float(window_s)
    n = 0
    bad = 0
    # summaries are retirement-time ordered (Telemetry appends at
    # retire): walk backwards and stop at the window edge, so a
    # per-step evaluation over a full 4096-deep deque costs the window
    # size, not the history size
    for s in reversed(summaries):
        at = s.get("at")
        if at is None:
            continue
        if at < lo:
            break
        n += 1
        if not on_time(s, ttft_deadline_s):
            bad += 1
    frac = bad / n if n else 0.0
    return {"requests": n, "bad": bad, "bad_fraction": round(frac, 4),
            "burn_rate": burn_rate(frac, slo_target) if n else 0.0,
            "window_s": float(window_s)}


def latency_percentiles(values_s, name: str = "latency",
                        ps=(50, 95, 99)) -> dict:
    """{p<q>_ms: ...} readout over a list of second-valued latencies, via
    the shared log-bucketed :class:`Histogram` (the single percentile
    implementation bench.py's traces all use)."""
    h = Histogram(name)
    for v in values_s:
        h.observe(v)
    q = h.percentiles(ps)
    return {f"p{p}_ms": round(q[p] * 1e3, 2) for p in ps}


def slo_report(summaries, ttft_deadline_s: float,
               window_s: float | None = None) -> dict:
    """SLO report over request summaries.

    ``summaries``: iterable of dicts with (at least) ``ttft_s``,
    ``tpot_s``, ``e2e_s``, ``tokens``, ``timed_out`` — exactly what
    ``Telemetry.request_summaries`` holds.  ``window_s``: the measurement
    wall-clock, enabling goodput tokens/s."""
    summaries = list(summaries)
    h_ttft = Histogram("ttft_s")
    h_tpot = Histogram("tpot_s")
    h_e2e = Histogram("e2e_s")
    good_req = 0
    good_tokens = 0
    total_tokens = 0
    for s in summaries:
        if s.get("ttft_s") is not None:
            h_ttft.observe(s["ttft_s"])
        if s.get("tpot_s") is not None:
            h_tpot.observe(s["tpot_s"])
        if s.get("e2e_s") is not None:
            h_e2e.observe(s["e2e_s"])
        tokens = int(s.get("tokens", 0))
        total_tokens += tokens
        if on_time(s, ttft_deadline_s):
            good_req += 1
            good_tokens += tokens

    def _q(h: Histogram) -> dict:
        q = h.percentiles()
        return {"p50_ms": round(q[50] * 1e3, 2),
                "p95_ms": round(q[95] * 1e3, 2),
                "p99_ms": round(q[99] * 1e3, 2),
                "count": h.count}

    n = len(summaries)
    rep = {
        "requests": n,
        "ttft": _q(h_ttft),
        "tpot": _q(h_tpot),
        "e2e": _q(h_e2e),
        "ttft_deadline_ms": round(ttft_deadline_s * 1e3, 2),
        "on_time_requests": good_req,
        "goodput_fraction": round(good_req / n, 4) if n else 0.0,
        "total_tokens": total_tokens,
        "goodput_tokens": good_tokens,
    }
    if window_s is not None and window_s > 0:
        rep["tokens_per_sec"] = round(total_tokens / window_s, 1)
        rep["goodput_tokens_per_sec"] = round(good_tokens / window_s, 1)
    return rep
