"""Request-lifecycle tracing: ordered per-request event records, engine
phase spans, Chrome-trace/Perfetto export, and a bridge into
``paddle_tpu.profiler`` so host spans land in the same timeline as jax
device traces.

Every request carries an ordered event record stamped with HOST timestamps
taken only at existing host-sync boundaries (the engine never adds a device
round-trip for telemetry — graftlint SYNC001 stays clean):

    submitted -> queued -> admitted -> prefill_chunk x N -> first_token
      -> decode_dispatch / verify_dispatch ... -> retired
    (+ preempted -> queued -> admitted ... on the self-healing path, and
     instant events: cache_hit, cow_copy, cache_evict, rejected, deadline)

The Chrome export derives PHASE SPANS from the lifecycle events with a tiny
state machine (queued: submitted->admitted, prefill: admitted->first_token,
decode: first_token->retired; preemption closes the open phase and re-opens
queued), nests them under one top-level span per request (tid = rid), and
emits everything else as instant events — the JSON loads directly in
chrome://tracing or https://ui.perfetto.dev.
"""
from __future__ import annotations

import json
import time
from collections import deque

__all__ = ["RequestTrace", "Tracer", "NULL_CONTEXT", "tracer_to_wire",
           "tracer_from_wire"]


class _NullContext:
    """Reusable no-op context (module singleton — telemetry-off code paths
    pay one flag check, not an allocation)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_CONTEXT = _NullContext()

# lifecycle events that OPEN a phase span (value: the span name)
_PHASE_OPEN = {"submitted": "queued", "admitted": "prefill",
               "first_token": "decode", "preempted": "queued"}
# events that CLOSE whatever phase is open
_PHASE_CLOSE = {"admitted", "first_token", "preempted", "retired"}
# terminal events: the request record moves to the completed ring
_TERMINAL = {"retired"}


class RequestTrace:
    """One request's ordered (event, host_ts, attrs) record."""

    __slots__ = ("rid", "events")

    def __init__(self, rid: int):
        self.rid = rid
        self.events: list[tuple[str, float, dict | None]] = []

    def names(self) -> list[str]:
        return [e[0] for e in self.events]

    def append(self, name: str, t: float, attrs: dict | None):
        self.events.append((name, t, attrs))


class Tracer:
    """Engine-level trace collector.

    Live requests index into ``_live``; terminal events move the record to
    a bounded completed ring (``max_completed``) so a long-running engine
    cannot grow without bound.  Engine-scope spans (step phases, dispatch
    host timings) land in their own bounded ring and export on a dedicated
    ``engine`` track."""

    def __init__(self, clock=time.perf_counter, bridge: bool = False,
                 max_completed: int = 1024, max_engine_events: int = 8192,
                 max_counter_events: int = 8192):
        self.clock = clock
        self.bridge = bool(bridge)
        self._live: dict[int, RequestTrace] = {}
        self._done: deque[RequestTrace] = deque(maxlen=max_completed)
        # (name, t0, t1 | None for instants, attrs)
        self._engine: deque[tuple] = deque(maxlen=max_engine_events)
        # (track name, t, {series: value}) — exported as Chrome counter
        # events ("ph": "C"), which Perfetto renders as counter TRACKS
        # (PagePool occupancy, queue depth) alongside the request spans
        self._counters: deque[tuple] = deque(maxlen=max_counter_events)

    # -- recording ---------------------------------------------------------
    def request_event(self, rid: int, name: str, t: float | None = None,
                      **attrs):
        tr = self._live.get(rid)
        if tr is None:
            tr = RequestTrace(rid)
            self._live[rid] = tr
        tr.append(name, self.clock() if t is None else t, attrs or None)
        if name in _TERMINAL:
            self._done.append(self._live.pop(rid))

    def engine_span(self, name: str, t0: float, t1: float, **attrs):
        self._engine.append((name, t0, t1, attrs or None))

    def engine_event(self, name: str, t: float | None = None, **attrs):
        self._engine.append((name, self.clock() if t is None else t, None,
                             attrs or None))

    def counter(self, track: str, t: float | None = None, **values):
        """One sample on a named counter track: every keyword becomes a
        series inside the track (Perfetto stacks them).  Values must be
        numbers."""
        self._counters.append((track, self.clock() if t is None else t,
                               {k: float(v) for k, v in values.items()}))

    def counter_events(self) -> list[tuple]:
        return list(self._counters)

    def annotation(self, name: str):
        """Context manager for the profiler bridge: when ``bridge`` is on,
        wraps the scope in ``paddle_tpu.profiler.host_annotation`` (a
        ``jax.profiler.TraceAnnotation``), so the host span shows up inside
        any active jax device trace next to the XLA ops it dispatched.
        Off-bridge: a shared no-op."""
        if not self.bridge:
            return NULL_CONTEXT
        from ..profiler import host_annotation
        return host_annotation(name)

    # -- introspection -----------------------------------------------------
    def get(self, rid: int) -> RequestTrace | None:
        tr = self._live.get(rid)
        if tr is not None:
            return tr
        for t in self._done:
            if t.rid == rid:
                return t
        return None

    def traces(self) -> list[RequestTrace]:
        out = list(self._done)
        out.extend(self._live.values())
        out.sort(key=lambda t: t.rid)
        return out

    # -- export ------------------------------------------------------------
    @staticmethod
    def _span_events(tr: RequestTrace) -> list[dict]:
        """Phase spans + instants for one request, nested under a single
        top-level span (chrome nesting = containment on one tid)."""
        if not tr.events:
            return []
        t_first = tr.events[0][1]
        t_last = tr.events[-1][1]
        tid = tr.rid + 1               # tid 0 is the engine track
        us = 1e6
        events = [{
            "name": f"request {tr.rid}", "cat": "request", "ph": "X",
            "pid": 0, "tid": tid, "ts": round(t_first * us, 3),
            "dur": round(max(0.0, (t_last - t_first)) * us, 3),
            "args": {"rid": tr.rid},
        }]
        open_name, open_t = None, 0.0
        for name, t, attrs in tr.events:
            if name in _PHASE_CLOSE and open_name is not None:
                events.append({
                    "name": open_name, "cat": "phase", "ph": "X",
                    "pid": 0, "tid": tid, "ts": round(open_t * us, 3),
                    "dur": round(max(0.0, t - open_t) * us, 3),
                })
                open_name = None
            if name in _PHASE_OPEN:
                open_name, open_t = _PHASE_OPEN[name], t
            if name not in _PHASE_OPEN and name not in _PHASE_CLOSE \
                    or name in ("preempted", "retired"):
                dur = (attrs or {}).get("dur")
                ev = {"name": name, "cat": "event",
                      "pid": 0, "tid": tid, "ts": round(t * us, 3)}
                if dur is not None:
                    # host-measured sub-span (e.g. one prefill chunk's
                    # dispatch) — export as a real slice, clamped inside
                    # the parent request span
                    ev["ph"] = "X"
                    ev["ts"] = round(max(t_first, t - float(dur)) * us, 3)
                    ev["dur"] = round(min(float(dur), t - t_first) * us, 3)
                else:
                    ev["ph"] = "i"
                    ev["s"] = "t"
                if attrs:
                    ev["args"] = {k: v for k, v in attrs.items()
                                  if k != "dur"}
                events.append(ev)
        if open_name is not None:
            # request still in flight: close the open phase at its last
            # known timestamp so the export is always loadable
            events.append({
                "name": open_name, "cat": "phase", "ph": "X",
                "pid": 0, "tid": tid, "ts": round(open_t * us, 3),
                "dur": round(max(0.0, t_last - open_t) * us, 3),
            })
        return events

    def to_chrome_trace(self) -> dict:
        """chrome://tracing / Perfetto-loadable dict.  Request tracks are
        tid = rid + 1; engine step/phase spans are tid 0."""
        us = 1e6
        events: list[dict] = [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "paddle_tpu serving engine"}},
            {"name": "thread_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "engine"}},
        ]
        for name, t0, t1, attrs in self._engine:
            if t1 is None:
                ev = {"name": name, "cat": "engine", "ph": "i", "s": "t",
                      "pid": 0, "tid": 0, "ts": round(t0 * us, 3)}
            else:
                ev = {"name": name, "cat": "engine", "ph": "X",
                      "pid": 0, "tid": 0, "ts": round(t0 * us, 3),
                      "dur": round(max(0.0, t1 - t0) * us, 3)}
            if attrs:
                ev["args"] = dict(attrs)
            events.append(ev)
        for track, t, values in self._counters:
            events.append({"name": track, "cat": "gauge", "ph": "C",
                           "pid": 0, "ts": round(t * us, 3),
                           "args": dict(values)})
        for tr in self.traces():
            events.append({"name": "thread_name", "ph": "M", "pid": 0,
                           "tid": tr.rid + 1,
                           "args": {"name": f"request {tr.rid}"}})
            events.extend(self._span_events(tr))
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


# -- cross-process wire form (ISSUE 17) -------------------------------------
# A worker process ships its Tracer to the supervisor over the `trace` RPC
# as plain JSON; the supervisor rebuilds an equivalent Tracer so the
# TraceStitcher sees worker tracks exactly like in-process replica tracks.
# Both sides must run on the SAME clock domain (the process fleet uses
# time.time end to end) or the stitched spans shear.

def _py(v):
    """JSON-safe scalar: numpy ints/floats -> python numbers."""
    if hasattr(v, "item") and not isinstance(v, (str, bytes)):
        try:
            return v.item()
        except (AttributeError, ValueError):
            return str(v)
    return v


def _py_attrs(attrs):
    return None if not attrs else {str(k): _py(v) for k, v in attrs.items()}


def tracer_to_wire(tracer: "Tracer") -> dict:
    """Serialize a Tracer (request records, engine spans, counter tracks)
    into a JSON-ready dict for the worker->supervisor ``trace`` RPC."""
    return {
        "requests": [{"rid": int(tr.rid),
                      "events": [[n, float(t), _py_attrs(a)]
                                 for n, t, a in tr.events]}
                     for tr in tracer.traces()],
        "engine": [[n, float(t0), None if t1 is None else float(t1),
                    _py_attrs(a)] for n, t0, t1, a in tracer._engine],
        "counters": [[track, float(t), {k: float(v) for k, v in vals.items()}]
                     for track, t, vals in tracer._counters],
    }


def tracer_from_wire(data: dict, clock=time.time) -> "Tracer":
    """Rebuild a Tracer from :func:`tracer_to_wire` output.  Records are
    replayed structurally (not through ``request_event``) so attr keys can
    never collide with parameter names and terminal placement matches the
    original exactly."""
    t = Tracer(clock=clock)
    for r in data.get("requests", ()):
        tr = RequestTrace(int(r["rid"]))
        for name, ts, attrs in r.get("events", ()):
            tr.append(name, float(ts), attrs or None)
        if tr.events and tr.events[-1][0] in _TERMINAL:
            t._done.append(tr)
        else:
            t._live[tr.rid] = tr
    for name, t0, t1, attrs in data.get("engine", ()):
        t._engine.append((name, float(t0),
                          None if t1 is None else float(t1), attrs or None))
    for track, ts, vals in data.get("counters", ()):
        t._counters.append((track, float(ts),
                            {k: float(v) for k, v in vals.items()}))
    return t
