"""Crash flight recorder: a bounded ring buffer of recent engine-step
events that dumps automatically when something goes wrong.

The resilience stack (PR 2) made the serving engine self-healing — but when
a run DID die (``EngineStalledError``, a ``RecompileBudgetError``, an
injected fault that never cleared) the postmortem evidence was gone: the
counters say *how many* preemptions happened, never *what the engine was
doing right before it stalled*.  The flight recorder keeps the last
``capacity`` events (admissions, evictions, preemptions, rejections,
deadline retirements, per-step summaries, faults) in a ring; on a trigger
the engine calls :meth:`FlightRecorder.dump`, which snapshots the ring into
``dumps`` (bounded) and optionally appends a JSON line to ``dump_path``.

Recording is O(1) (deque append of a small dict); the ring holds plain
Python values only — no device arrays, no syncs."""
from __future__ import annotations

import json
import threading
import time
from collections import deque

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded ring of recent engine events + bounded dump history."""

    def __init__(self, capacity: int = 256, clock=time.perf_counter,
                 max_dumps: int = 8, dump_path: str | None = None):
        self.capacity = int(capacity)
        self.clock = clock
        self.max_dumps = int(max_dumps)
        self.dump_path = dump_path
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._seq = 0
        self.dumps: list[dict] = []
        # engines, fleet watchdogs and exporter scrape threads all hit
        # one recorder: the seq counter, the ring snapshot (iterating a
        # deque while another thread appends raises RuntimeError) and
        # the dump-history trim must be atomic
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def record(self, event: str, **attrs):
        with self._lock:
            self._seq += 1
            rec = {"seq": self._seq, "t": float(self.clock()),
                   "event": event}
            if attrs:
                rec.update(attrs)
            self._ring.append(rec)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def event_names(self) -> list[str]:
        with self._lock:
            return [r["event"] for r in self._ring]

    def dump(self, reason: str, **extra) -> dict:
        """Snapshot the ring (the full recent-event window) under `reason`.
        Returns the dump dict; also kept in ``self.dumps`` (last
        ``max_dumps``) and appended as one JSON line to ``dump_path`` when
        configured — the artifact a postmortem actually reads."""
        with self._lock:
            d = {"reason": reason, "at": float(self.clock()),
                 "total_events": self._seq, "events": list(self._ring)}
            if extra:
                d["extra"] = dict(extra)
            self.dumps.append(d)
            if len(self.dumps) > self.max_dumps:
                del self.dumps[: len(self.dumps) - self.max_dumps]
        if self.dump_path:
            try:
                with open(self.dump_path, "a") as f:
                    json.dump(d, f)
                    f.write("\n")
            except OSError:
                pass        # a full disk must never take the engine down
        return d

    def last_dump(self) -> dict | None:
        return self.dumps[-1] if self.dumps else None

    @staticmethod
    def format_dump(d: dict) -> str:
        """Human-readable rendering of one dump (README §Observability
        documents how to read it)."""
        lines = [f"flight-recorder dump: {d['reason']} at t={d['at']:.6f} "
                 f"({len(d['events'])} of {d['total_events']} events "
                 f"retained)"]
        for e in d["events"]:
            attrs = {k: v for k, v in e.items()
                     if k not in ("seq", "t", "event")}
            lines.append(f"  #{e['seq']:>6} t={e['t']:.6f} {e['event']:<12}"
                         + (f" {attrs}" if attrs else ""))
        if "extra" in d:
            lines.append(f"  extra: {d['extra']}")
        return "\n".join(lines)
