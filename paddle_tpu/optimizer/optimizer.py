"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:128).

Design: each optimizer implements a *functional* per-parameter update
``_update(p, g, state, lr) -> (new_p, new_state)`` over raw jax arrays. The
eager ``step()`` applies it in place (dygraph parity); jitted train steps call
``apply_gradients_functional`` on whole pytrees so the update fuses into the
compiled step (the fused adamw kernel analog — XLA fuses the elementwise
chain into one pass over HBM).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dispatch import no_grad
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = self._flatten_params(parameters)
        self._param_groups = self._build_groups(parameters)
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._accumulators: Dict[int, Dict[str, jnp.ndarray]] = {}
        self._global_step = 0

    @staticmethod
    def _flatten_params(parameters):
        if parameters is None:
            return []
        out = []
        for p in parameters:
            if isinstance(p, dict):
                out.extend(p["params"])
            else:
                out.append(p)
        return out

    def _build_groups(self, parameters):
        if parameters is None:
            return []
        groups = []
        plain = []
        for p in parameters:
            if isinstance(p, dict):
                groups.append(dict(p))
            else:
                plain.append(p)
        if plain:
            groups.insert(0, {"params": plain})
        return groups

    # -- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state -------------------------------------------------------------
    def _get_state(self, p: Parameter) -> Dict[str, jnp.ndarray]:
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = self._init_state(p._value)
        return self._accumulators[key]

    def _init_state(self, value) -> Dict[str, jnp.ndarray]:
        return {}

    def _update(self, p, g, state, lr, **group_kw):
        raise NotImplementedError

    # -- the eager step ----------------------------------------------------
    @no_grad()
    def step(self):
        lr = self.get_lr()
        params_grads = [(p, p._grad) for p in self._parameter_list
                        if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        wd = self._weight_decay
        for p, g in params_grads:
            if g is None:
                continue
            gv = g._value if isinstance(g, Tensor) else g
            pv = p._value
            # per-parameter regularizer objects (reference regularizer.py via
            # ParamAttr) override the optimizer-global weight_decay
            preg = getattr(p, "regularizer", None)
            if preg is not None and callable(preg):
                gv = gv + preg(pv)
            elif wd is not None and self._decoupled_wd is False:
                if callable(wd):          # L1Decay/L2Decay instance
                    gv = gv + wd(pv)
                else:
                    gv = gv + float(wd) * pv
            state = self._get_state(p)
            plr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if hasattr(p, "optimize_attr") else lr
            new_p, new_state = self._update(pv, gv, state, plr)
            p._set_value(new_p)
            # keyed per parameter: bounded by the model, not steps
            # graftlint: disable=LEAK001
            self._accumulators[id(p)] = new_state
        self._global_step += 1

    _decoupled_wd = False  # True for AdamW-style optimizers

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, [(p, p._grad) for p in self._parameter_list]

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def finish_step(self):
        """Post-step bookkeeping shared by compiled train steps: advance the
        LR scheduler (if any) and the global step counter."""
        if isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.step()
        self._global_step += 1

    # -- functional application (jit path) ---------------------------------
    def apply_gradients_functional(self, params: dict, grads: dict, opt_state: dict,
                                   lr=None, lr_scales: Optional[dict] = None):
        """Pure update over {name: value} pytrees; used by compiled train
        steps. Returns (new_params, new_opt_state).

        lr_scales: optional {name: float} per-param LR multipliers (the
        optimize_attr['learning_rate'] values the eager step() honors)."""
        lr = self.get_lr() if lr is None else lr
        wd = self._weight_decay
        new_params, new_state = {}, {}
        for name, pv in params.items():
            gv = grads.get(name)
            if gv is None:
                new_params[name] = pv
                new_state[name] = opt_state.get(name, {})
                continue
            if wd is not None and self._decoupled_wd is False:
                # same L1Decay/L2Decay-object handling as the eager step()
                gv = gv + (wd(pv) if callable(wd) else float(wd) * pv)
            st = opt_state.get(name)
            if st is None or not st:
                st = self._init_state(pv)
            plr = lr * lr_scales[name] if lr_scales and name in lr_scales else lr
            np_, ns = self._update(pv, gv, st, plr)
            new_params[name] = np_
            new_state[name] = ns
        return new_params, new_state

    def init_opt_state(self, params: dict) -> dict:
        return {name: self._init_state(v) for name, v in params.items()}

    # -- checkpointing -----------------------------------------------------
    def state_dict(self):
        sd = {}
        names = self._param_names()
        for p in self._parameter_list:
            state = self._accumulators.get(id(p))
            if state is None:
                continue
            pname = names[id(p)]
            for k, v in state.items():
                sd[f"{pname}.{k}"] = Tensor(v)
        sd["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict):
        names = self._param_names()
        inv = {v: k for k, v in names.items()}
        for p in self._parameter_list:
            pname = names[id(p)]
            state = {}
            template = self._init_state(p._value)
            for k in template:
                key = f"{pname}.{k}"
                if key in state_dict:
                    v = state_dict[key]
                    state[k] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
                else:
                    state[k] = template[k]
            self._accumulators[id(p)] = state
        self._global_step = int(state_dict.get("global_step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])

    def _param_names(self):
        names = {}
        for i, p in enumerate(self._parameter_list):
            names[id(p)] = p.name or f"param_{i}"
        return names
