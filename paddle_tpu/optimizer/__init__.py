"""paddle.optimizer parity namespace."""
from __future__ import annotations

from .optimizer import Optimizer
from .optimizers import (SGD, Momentum, Adam, AdamW, Adamax, Adagrad, RMSProp,
                         Adadelta, Lamb, NAdam, RAdam, ASGD, Rprop)
from .lbfgs import LBFGS
from . import lr

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "RMSProp", "Adadelta", "Lamb", "NAdam", "RAdam", "ASGD", "Rprop", "LBFGS", "lr"]
