"""L-BFGS optimizer (reference: python/paddle/optimizer/lbfgs.py — Nocedal &
Wright Algorithm 7.5 with optional strong-Wolfe line search).

Closure-driven like the reference: `opt.step(closure)` re-evaluates the
loss/grads as the line search probes points. History and two-loop recursion
run on flattened f32 vectors (jnp on-device; the control flow is host-side,
matching the reference's dygraph implementation)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import no_grad
from .optimizer import Optimizer

__all__ = ["LBFGS"]


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate=learning_rate, parameters=parameters,
                         weight_decay=weight_decay, grad_clip=grad_clip,
                         name=name)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self._max_iter = max_iter
        self._max_eval = max_eval if max_eval is not None \
            else int(max_iter * 1.25)
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history = history_size
        self._line_search = line_search_fn
        self._s, self._y = [], []   # curvature history
        self._prev_flat_grad = None
        self._n_evals = 0

    # -- flat <-> param views ----------------------------------------------
    def _gather_flat(self, attr="_value"):
        return jnp.concatenate([
            jnp.ravel(getattr(p, attr)).astype(jnp.float32)
            for p in self._parameter_list])

    def _flat_grad(self):
        outs = []
        for p in self._parameter_list:
            g = p._grad
            gv = g._value if isinstance(g, Tensor) else g
            outs.append(jnp.ravel(
                gv if gv is not None else jnp.zeros_like(p._value)
            ).astype(jnp.float32))
        return jnp.concatenate(outs)

    def _set_flat(self, flat):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p._value.shape)) if p._value.shape else 1
            chunk = flat[off:off + n].reshape(p._value.shape)
            p._set_value(chunk.astype(p._value.dtype))
            off += n

    def _eval(self, closure, flat_x):
        self._set_flat(flat_x)
        loss = closure()
        self._n_evals += 1
        lv = float(loss.numpy() if isinstance(loss, Tensor) else loss)
        return lv, self._flat_grad()

    # -- two-loop recursion -------------------------------------------------
    def _direction(self, g):
        q = -g
        if not self._s:
            return q
        alphas = []
        for s, y in reversed(list(zip(self._s, self._y))):
            rho = 1.0 / float(jnp.vdot(y, s))
            a = rho * float(jnp.vdot(s, q))
            q = q - a * y
            alphas.append((a, rho, s, y))
        s_l, y_l = self._s[-1], self._y[-1]
        gamma = float(jnp.vdot(s_l, y_l)) / float(jnp.vdot(y_l, y_l))
        q = q * gamma
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.vdot(y, q))
            q = q + (a - b) * s
        return q

    def _strong_wolfe(self, closure, x, f0, g0, d, t, c1=1e-4, c2=0.9,
                      max_ls=25):
        """Strong-Wolfe line search (reference lbfgs.py _strong_wolfe)."""
        d_norm = float(jnp.max(jnp.abs(d)))
        gtd0 = float(jnp.vdot(g0, d))
        if gtd0 > -1e-16:
            return f0, g0, 0.0
        f_prev, g_prev, t_prev = f0, g0, 0.0
        for i in range(max_ls):
            f_new, g_new = self._eval(closure, x + t * d)
            gtd = float(jnp.vdot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or (i > 0 and f_new >= f_prev):
                return self._zoom(closure, x, f0, gtd0, d, t_prev, t, f_prev,
                                  c1, c2)
            if abs(gtd) <= -c2 * gtd0:
                return f_new, g_new, t
            if gtd >= 0:
                return self._zoom(closure, x, f0, gtd0, d, t, t_prev, f_new,
                                  c1, c2)
            f_prev, g_prev, t_prev = f_new, g_new, t
            t *= 2.0
            if t * d_norm > 1e10:
                break
        return f_new, g_new, t

    def _zoom(self, closure, x, f0, gtd0, d, lo, hi, f_lo, c1, c2,
              max_zoom=25):
        for _ in range(max_zoom):
            t = 0.5 * (lo + hi)
            f_new, g_new = self._eval(closure, x + t * d)
            gtd = float(jnp.vdot(g_new, d))
            if f_new > f0 + c1 * t * gtd0 or f_new >= f_lo:
                hi = t
            else:
                if abs(gtd) <= -c2 * gtd0:
                    return f_new, g_new, t
                if gtd * (hi - lo) >= 0:
                    hi = lo
                lo, f_lo = t, f_new
            if abs(hi - lo) < 1e-9:
                break
        return f_new, g_new, t

    # -- the step ------------------------------------------------------------
    @no_grad()
    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure that "
                             "re-evaluates the model and returns the loss")
        self._n_evals = 0
        x = self._gather_flat()
        with_grad_closure = closure

        def eval_closure():
            for p in self._parameter_list:
                if hasattr(p, "clear_grad"):
                    p.clear_grad()
            from ..core.dispatch import enable_grad
            with enable_grad():
                loss = with_grad_closure()
                # reference lbfgs.py: step() owns the backward; the user
                # closure just builds the loss
                if isinstance(loss, Tensor) and not loss.stop_gradient:
                    loss.backward()
            return loss

        f, g = self._eval(eval_closure, x)
        orig_loss = f
        for _it in range(self._max_iter):
            if float(jnp.max(jnp.abs(g))) <= self._tol_grad:
                break
            if self._n_evals >= self._max_eval:
                break
            d = self._direction(g)
            lr = float(self.get_lr())
            t = min(1.0, 1.0 / max(float(jnp.sum(jnp.abs(g))), 1e-10)) * lr \
                if _it == 0 and not self._s else lr
            g_old = g
            if self._line_search == "strong_wolfe":
                f_new, g_new, t = self._strong_wolfe(eval_closure, x, f, g, d,
                                                     t)
                if t == 0.0:
                    break
                x_new = x + t * d
            else:
                x_new = x + t * d
                f_new, g_new = self._eval(eval_closure, x_new)
            s = x_new - x
            y = g_new - g_old
            if float(jnp.vdot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self._history:
                    self._s.pop(0)
                    self._y.pop(0)
            if abs(f_new - f) < self._tol_change and \
                    float(jnp.max(jnp.abs(s))) < self._tol_change:
                x, f, g = x_new, f_new, g_new
                break
            x, f, g = x_new, f_new, g_new
        self._set_flat(x)
        self._global_step += 1
        return Tensor(jnp.asarray(orig_loss, jnp.float32))
