"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,
adamw,adamax,adagrad,rmsprop,adadelta,lamb}.py; fused CUDA kernels
paddle/phi/kernels/gpu/adamw_kernel.cu — here the per-param update is a short
elementwise chain XLA fuses into one HBM pass).
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad", "RMSProp",
           "Adadelta", "Lamb", "NAdam", "RAdam", "ASGD", "Rprop"]


def _f32(v):
    return v.astype(jnp.float32)


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update(self, p, g, state, lr):
        return (p - lr * g.astype(p.dtype)).astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, rescale_grad=1.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, value):
        return {"velocity": jnp.zeros_like(_f32(value))}

    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        v = self._momentum * state["velocity"] + g32
        if self._nesterov:
            upd = g32 + self._momentum * v
        else:
            upd = v
        return (p - lr * upd.astype(p.dtype)).astype(p.dtype), {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._amsgrad = amsgrad

    def _init_state(self, value):
        s = {"moment1": jnp.zeros_like(_f32(value)),
             "moment2": jnp.zeros_like(_f32(value)),
             "beta1_pow": jnp.ones((), jnp.float32),
             "beta2_pow": jnp.ones((), jnp.float32)}
        if getattr(self, "_amsgrad", False):
            s["moment2_max"] = jnp.zeros_like(_f32(value))
        return s

    def _adam_core(self, p, g, state, lr, decoupled_wd=0.0):
        g32 = _f32(g)
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        if not getattr(self, "_amsgrad", False):
            # fused Pallas update when registered (TPU): one kernel for the
            # whole (p, m, v) stream instead of an XLA elementwise chain
            from ..core.dispatch import get_kernel
            fused_fn = get_kernel("adamw_fused")
            if fused_fn is not None:
                res = fused_fn(p, g, state["moment1"], state["moment2"],
                               lr=lr, beta1=b1, beta2=b2, eps=self._eps,
                               weight_decay=decoupled_wd,
                               bias1=1.0 - b1p, bias2=1.0 - b2p)
                if res is not None:
                    np_, nm, nv = res
                    return np_, {"moment1": nm, "moment2": nv,
                                 "beta1_pow": b1p, "beta2_pow": b2p}
        m1 = b1 * state["moment1"] + (1 - b1) * g32
        m2 = b2 * state["moment2"] + (1 - b2) * g32 * g32
        new = {"moment1": m1, "moment2": m2, "beta1_pow": b1p, "beta2_pow": b2p}
        m1h = m1 / (1 - b1p)
        if self._amsgrad:
            m2max = jnp.maximum(state["moment2_max"], m2)
            new["moment2_max"] = m2max
            m2h = m2max / (1 - b2p)
        else:
            m2h = m2 / (1 - b2p)
        p32 = _f32(p)
        if decoupled_wd:
            p32 = p32 * (1 - lr * decoupled_wd)
        out = p32 - lr * m1h / (jnp.sqrt(m2h) + self._eps)
        return out.astype(p.dtype), new

    def _update(self, p, g, state, lr):
        return self._adam_core(p, g, state, lr)


class AdamW(Adam):
    """Decoupled weight decay (reference python/paddle/optimizer/adamw.py)."""

    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._wd = weight_decay
        self._apply_decay_param_fun = apply_decay_param_fun
        self._weight_decay = None  # handled decoupled

    def _update(self, p, g, state, lr):
        wd = self._wd if isinstance(self._wd, float) else float(self._wd)
        return self._adam_core(p, g, state, lr, decoupled_wd=wd)

    def step(self):
        # per-param decay exemption via apply_decay_param_fun
        if self._apply_decay_param_fun is None:
            return super().step()
        fn = self._apply_decay_param_fun
        names = self._param_names()
        real_wd = self._wd
        lr = self.get_lr()
        params_grads = [(p, p._grad) for p in self._parameter_list
                        if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            state = self._get_state(p)
            wd = real_wd if fn(names[id(p)]) else 0.0
            new_p, new_state = self._adam_core(p._value, g._value, state, lr,
                                               decoupled_wd=wd)
            p._set_value(new_p)
            # keyed per parameter: bounded by the model, not steps
            # graftlint: disable=LEAK001
            self._accumulators[id(p)] = new_state
        self._global_step += 1


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, value):
        return {"moment": jnp.zeros_like(_f32(value)),
                "inf_norm": jnp.zeros_like(_f32(value)),
                "beta1_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        b1p = state["beta1_pow"] * self._beta1
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        out = _f32(p) - (lr / (1 - b1p)) * m / (u + self._eps)
        return out.astype(p.dtype), {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, value):
        return {"moment": jnp.full_like(_f32(value), self._init_acc)}

    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        m = state["moment"] + g32 * g32
        out = _f32(p) - lr * g32 / (jnp.sqrt(m) + self._eps)
        return out.astype(p.dtype), {"moment": m}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, value):
        s = {"mean_square": jnp.zeros_like(_f32(value)),
             "momentum": jnp.zeros_like(_f32(value))}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(_f32(value))
        return s

    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g32 * g32
        new = {"mean_square": ms}
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            new["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum"] + lr * g32 / denom
        new["momentum"] = mom
        return (_f32(p) - mom).astype(p.dtype), new


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps, self._rho = epsilon, rho

    def _init_state(self, value):
        return {"avg_squared_grad": jnp.zeros_like(_f32(value)),
                "avg_squared_update": jnp.zeros_like(_f32(value))}

    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g32 * g32
        upd = g32 * jnp.sqrt(state["avg_squared_update"] + self._eps) / jnp.sqrt(asg + self._eps)
        asu = self._rho * state["avg_squared_update"] + (1 - self._rho) * upd * upd
        return (_f32(p) - lr * upd).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference python/paddle/optimizer/lamb.py)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, value):
        return {"moment1": jnp.zeros_like(_f32(value)),
                "moment2": jnp.zeros_like(_f32(value)),
                "beta1_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32)}

    def _update(self, p, g, state, lr, wd=None):
        g32 = _f32(g)
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m1 = b1 * state["moment1"] + (1 - b1) * g32
        m2 = b2 * state["moment2"] + (1 - b2) * g32 * g32
        m1h = m1 / (1 - b1p)
        m2h = m2 / (1 - b2p)
        wd = self._lamb_wd if wd is None else wd
        r = m1h / (jnp.sqrt(m2h) + self._eps) + wd * _f32(p)
        w_norm = jnp.linalg.norm(_f32(p))
        r_norm = jnp.linalg.norm(r)
        ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        out = _f32(p) - lr * ratio * r
        return out.astype(p.dtype), {"moment1": m1, "moment2": m2,
                                     "beta1_pow": b1p, "beta2_pow": b2p}


class NAdam(Adam):
    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m1 = b1 * state["moment1"] + (1 - b1) * g32
        m2 = b2 * state["moment2"] + (1 - b2) * g32 * g32
        m1h = (b1 * m1 + (1 - b1) * g32) / (1 - b1p * b1)
        m2h = m2 / (1 - b2p)
        out = _f32(p) - lr * m1h / (jnp.sqrt(m2h) + self._eps)
        return out.astype(p.dtype), {"moment1": m1, "moment2": m2,
                                     "beta1_pow": b1p, "beta2_pow": b2p}


class RAdam(Adam):
    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        b1, b2 = self._beta1, self._beta2
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        t = jnp.log(b2p) / jnp.log(b2)  # step count
        m1 = b1 * state["moment1"] + (1 - b1) * g32
        m2 = b2 * state["moment2"] + (1 - b2) * g32 * g32
        m1h = m1 / (1 - b1p)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2 * t * b2p / (1 - b2p)
        def rect(_):
            r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf) /
                         ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            return r * m1h / (jnp.sqrt(m2 / (1 - b2p)) + self._eps)
        def norect(_):
            return m1h
        upd = jnp.where(rho_t > 5.0, rect(None), norect(None))
        out = _f32(p) - lr * upd
        return out.astype(p.dtype), {"moment1": m1, "moment2": m2,
                                     "beta1_pow": b1p, "beta2_pow": b2p}


class ASGD(Optimizer):
    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._batch_num = batch_num

    def _init_state(self, value):
        return {"d": jnp.zeros_like(_f32(value)),
                "ys": jnp.zeros((self._batch_num,) + value.shape, jnp.float32),
                "idx": jnp.zeros((), jnp.int32)}

    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        i = state["idx"] % self._batch_num
        old_y = state["ys"][i]
        d = state["d"] - old_y + g32
        ys = state["ys"].at[i].set(g32)
        out = _f32(p) - lr * d / self._batch_num
        return out.astype(p.dtype), {"d": d, "ys": ys, "idx": state["idx"] + 1}


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _init_state(self, value):
        return {"prev_grad": jnp.zeros_like(_f32(value)),
                "lrs": jnp.full_like(_f32(value), float(self._learning_rate)
                                     if not callable(self._learning_rate) else 1e-2)}

    def _update(self, p, g, state, lr):
        g32 = _f32(g)
        sign = jnp.sign(g32 * state["prev_grad"])
        eta_m, eta_p = self._etas
        factor = jnp.where(sign > 0, eta_p, jnp.where(sign < 0, eta_m, 1.0))
        lrs = jnp.clip(state["lrs"] * factor, self._lr_range[0], self._lr_range[1])
        g_eff = jnp.where(sign < 0, 0.0, g32)
        out = _f32(p) - lrs * jnp.sign(g_eff)
        return out.astype(p.dtype), {"prev_grad": g_eff, "lrs": lrs}
