"""HybridParallelOptimizer (reference: fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:275): wraps the inner optimizer, extending grad
clip to global-norm across model-parallel shards and syncing params across
groups after step.

TPU note: inside a compiled sharded train step the cross-shard grad-norm terms
come out of the same psum XLA emits for gradient sync; this wrapper covers the
eager path.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.clip import ClipGradByGlobalNorm

__all__ = ["HybridParallelOptimizer"]


class _HybridClip(ClipGradByGlobalNorm):
    """Global-norm clip whose norm accumulates across mp/pp/sharding shards
    (reference HybridParallelClipGrad)."""

    def __init__(self, inner_clip, hcg):
        super().__init__(inner_clip.clip_norm)
        self._hcg = hcg

    def _global_norm_sq(self, params_grads):
        total = super()._global_norm_sq(params_grads)
        # across-model-parallel reduction: traced values psum over 'mp'
        from ..communication.collectives import _is_traced
        import jax
        if _is_traced(total):
            for axis in ("mp", "pp", "sharding"):
                try:
                    total = jax.lax.psum(total, axis)
                except NameError:
                    pass
        return total


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if optimizer._grad_clip is not None and \
                isinstance(optimizer._grad_clip, ClipGradByGlobalNorm) and \
                hcg.get_model_parallel_world_size() > 1:
            optimizer._grad_clip = _HybridClip(optimizer._grad_clip, hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters, no_grad_set)

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad
