"""Tensor-parallel layers (reference: python/paddle/distributed/fleet/layers/
mpu/mp_layers.py — VocabParallelEmbedding :49, ColumnParallelLinear :336,
RowParallelLinear :543, ParallelCrossEntropy :744; identity/allreduce PyLayers
mp_ops.py:40,239).

TPU-native design: two modes share one layer class.

* **GSPMD mode** (default, inside pjit): weights carry a NamedSharding over
  the 'mp' mesh axis and activations get `with_sharding_constraint`; XLA's
  partitioner inserts exactly the identity/allreduce pattern the reference
  hand-writes (f/g ops of Megatron). This is how the 119 C++ SPMD rules
  collapse into the compiler.
* **explicit mode** (inside shard_map, where the mesh axis is a named axis in
  scope): forward uses `lax.psum` directly, matching the reference PyLayers
  one-for-one.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ....core.tensor import Tensor, Parameter
from ....core.dispatch import op_call
from ....nn.layer import Layer
from ....nn import functional as F_nn
from ...topology import get_default_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
           "ParallelCrossEntropy", "mp_axis_in_scope", "shard_param",
           "constrain"]


def mp_axis_in_scope(axis="mp") -> bool:
    """True when called inside shard_map over `axis` (explicit-collective mode)."""
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


def shard_param(p: Parameter, spec_entries) -> Parameter:
    """Attach a NamedSharding over the default mesh to a parameter's value."""
    mesh = get_default_mesh()
    entries = [e if (e is None or e in mesh.axis_names) else None
               for e in spec_entries]
    if all(e is None for e in entries):
        return p
    try:
        sh = NamedSharding(mesh, P(*entries))
        p._set_value(jax.device_put(p._value, sh))
    except Exception:
        pass  # mesh may not cover all devices in tests; weights stay replicated
    return p


def constrain(x, *entries, axis_filter=None):
    """with_sharding_constraint on a Tensor when tracing under pjit."""
    mesh = get_default_mesh()
    ee = tuple(e if (e is None or e in mesh.axis_names) else None for e in entries)
    def impl(v):
        try:
            return jax.lax.with_sharding_constraint(v, NamedSharding(mesh, P(*ee)))
        except Exception:
            return v
    return op_call("shard_constraint", impl, x)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp' (reference :49)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num = num_embeddings
        self._dim = embedding_dim
        from ....nn.initializer import Normal
        from ....param_attr import ParamAttr
        attr = ParamAttr._to_attr(weight_attr)
        if isinstance(attr, ParamAttr) and attr.initializer is None:
            attr.initializer = Normal(0.0, 0.02)
        self.weight = self.create_parameter((num_embeddings, embedding_dim), attr=attr)
        shard_param(self.weight, ("mp", None))

    def forward(self, x):
        if mp_axis_in_scope("mp"):
            # explicit Megatron path: mask out-of-shard ids, psum results
            def impl(w, ids):
                n = jax.lax.psum(1, "mp")
                r = jax.lax.axis_index("mp")
                per = w.shape[0]
                lo = r * 0 + 0  # local weights are already the shard
                ids32 = ids.astype(jnp.int32)
                local = ids32 - r * per
                ok = (local >= 0) & (local < per)
                safe = jnp.where(ok, local, 0)
                emb = w[safe]
                emb = jnp.where(ok[..., None], emb, 0.0)
                return jax.lax.psum(emb, "mp")
            return op_call("vocab_parallel_embedding", impl, self.weight, x)
        out = F_nn.embedding(x, self.weight)
        return constrain(out, *([None] * (out.ndim - 1)), None)


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over 'mp' (reference :336)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._in = in_features
        self._out = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter((in_features, out_features), attr=weight_attr)
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias in (True, None) else None
        shard_param(self.weight, (None, "mp"))
        if self.bias is not None:
            shard_param(self.bias, ("mp",))

    def forward(self, x):
        if mp_axis_in_scope("mp"):
            def impl(v, w, *b):
                out = v @ w  # local shard of columns
                if b:
                    out = out + b[0]
                return out
            args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
            out = op_call("column_parallel_linear", impl, *args)
            if self.gather_output:
                def gather(v):
                    g = jax.lax.all_gather(v, "mp")  # [mp, ..., out/mp]
                    return jnp.moveaxis(g, 0, -2).reshape(v.shape[:-1] + (-1,))
                out = op_call("mp_allgather", gather, out)
            return out
        out = F_nn.linear(x, self.weight, self.bias)
        if not self.gather_output:
            out = constrain(out, *([None] * (out.ndim - 1)), "mp")
        return out


class RowParallelLinear(Layer):
    """Linear with in_features sharded over 'mp' (reference :543)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter((in_features, out_features), attr=weight_attr)
        self.bias = self.create_parameter((out_features,), is_bias=True) if has_bias else None
        shard_param(self.weight, ("mp", None))

    def forward(self, x):
        if mp_axis_in_scope("mp"):
            def impl(v, w, *b):
                part = v @ w
                out = jax.lax.psum(part, "mp")
                if b:
                    out = out + b[0]
                return out
            args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
            return op_call("row_parallel_linear", impl, *args)
        if self.input_is_parallel:
            x = constrain(x, *([None] * (x.ndim - 1)), "mp")
        out = F_nn.linear(x, self.weight, self.bias)
        return constrain(out, *([None] * out.ndim))


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE (reference :744): logits sharded on the class
    dim over 'mp'; loss computed without materializing full logits."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        if mp_axis_in_scope("mp"):
            def impl(logits, lab):
                per = logits.shape[-1]
                r = jax.lax.axis_index("mp")
                # stable logsumexp over the sharded class dim
                lmax = jax.lax.pmax(jnp.max(logits, -1, keepdims=True), "mp")
                z = jnp.exp(logits - lmax)
                denom = jax.lax.psum(jnp.sum(z, -1, keepdims=True), "mp")
                lse = jnp.log(denom) + lmax
                ids = lab.astype(jnp.int32)
                local = ids - r * per
                ok = (local >= 0) & (local < per)
                safe = jnp.where(ok, local, 0)
                picked = jnp.take_along_axis(logits, safe[..., None], -1)[..., 0]
                picked = jnp.where(ok, picked, 0.0)
                picked = jax.lax.psum(picked, "mp")
                return (lse[..., 0] - picked)[..., None]
            return op_call("parallel_cross_entropy", impl, input, label)
        loss = F_nn.cross_entropy(input, label, reduction="none",
                                  ignore_index=self.ignore_index)
        from ....tensor.manipulation import unsqueeze
        return unsqueeze(loss, -1)
