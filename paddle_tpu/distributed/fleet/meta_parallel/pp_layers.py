"""Pipeline layer description (reference: fleet/meta_parallel/parallel_layers/
pp_layers.py — PipelineLayer :258, LayerDesc :57, SegmentLayers :93).

PipelineLayer declares the model as an ordered list of LayerDescs and segments
them into stages. On TPU the stages map onto the 'pp' mesh axis: the compiled
schedule stacks per-stage parameters and runs microbatches with
`lax.ppermute` hops between neighbors (see pipeline_parallel.py).
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional

import numpy as np

from ....nn.layer import Layer
from ....nn.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer) and not callable(layer_cls):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer reused across stages (embedding/output head tying,
    reference pp_layers.py SharedLayerDesc)."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split N layer descs into num_parts stages (reference :93) — 'uniform'
    or 'layer:<ClassName>' boundary strategy."""

    def __init__(self, layers_desc, num_parts, method="uniform", num_virtual_pipeline_stage=None):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.descs)
        if self.method == "uniform":
            return self.uniform(n, self.num_parts)
        if self.method.startswith("layer:"):
            name = self.method.split(":", 1)[1]
            idxs = [i for i, d in enumerate(self.descs)
                    if getattr(getattr(d, "layer_cls", type(d)), "__name__", "") == name]
            if len(idxs) < self.num_parts:
                return self.uniform(n, self.num_parts)
            # distribute the named layers evenly over stages
            per = len(idxs) / self.num_parts
            bounds = [0]
            for p in range(1, self.num_parts):
                bounds.append(idxs[int(round(p * per))])
            bounds.append(n)
            return bounds
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        base = num_items // num_parts
        rem = num_items % num_parts
        bounds = [0]
        for i in range(num_parts):
            bounds.append(bounds[-1] + base + (1 if i < rem else 0))
        return bounds


class PipelineLayer(Layer):
    """reference pp_layers.py:258. Holds the full desc list; builds the local
    stage's layers (single-controller TPU builds all stages and shards their
    params over 'pp' in the compiled schedule)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._descs = list(layers)
        self._topo = topology
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self.segment_parts = SegmentLayers(self._descs, self._num_stages,
                                           seg_method).do_segment()
        # single-controller: materialize every stage; stage boundaries kept
        self._shared = {}
        built = []
        for d in self._descs:
            built.append(self._build_one(d))
        self.run_function = LayerList(built)

    def _build_one(self, d):
        if isinstance(d, SharedLayerDesc):
            if d.layer_name not in self._shared:
                self._shared[d.layer_name] = d.build_layer()
            base = self._shared[d.layer_name]
            if d.forward_func is None:
                return base
            fwd = d.forward_func

            class _SharedFwd(Layer):
                def __init__(self):
                    super().__init__()
                    self.inner = base

                def forward(self, *args, **kw):
                    return fwd(self.inner, *args, **kw)
            return _SharedFwd()
        if isinstance(d, LayerDesc):
            return d.build_layer()
        if isinstance(d, Layer):
            return d
        if callable(d):
            class _Fn(Layer):
                def forward(self, *args, **kw):
                    return d(*args, **kw)
            return _Fn()
        raise TypeError(f"bad pipeline item {d!r}")

    def get_stage_layers(self, stage: int):
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return [self.run_function[i] for i in range(lo, hi)]

    @property
    def loss_fn(self):
        return self._loss_fn

    def forward(self, x):
        for l in self.run_function:
            x = l(x) if not isinstance(x, tuple) else l(*x)
        return x
