"""Pipeline-parallel runtime (reference: fleet/meta_parallel/
pipeline_parallel.py — 1F1B PipelineParallel :242, interleaved :1308,
F-then-B :2396; P2P p2p_communication.py:651).

TPU-native schedule: XLA is a static-graph world, so the schedule is expressed
as a compiled microbatch loop (`paddle_tpu.parallel.pipeline` provides the
shard_map/ppermute compiled schedule used by the perf path). This class keeps
the reference's train_batch contract — microbatching + gradient accumulation
with 1F1B-ordered execution — and executes stages in-process, which on a
single controller is semantically identical (the compiled path fuses it onto
the 'pp' axis).
"""
from __future__ import annotations

from typing import List

import numpy as np

from ....core.tensor import Tensor
from ....nn.layer import Layer
from ....tensor import manipulation as manip

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pp_cfg = strategy.hybrid_configs.get("pp_configs") if strategy else None
        self._micro_batch_size = getattr(pp_cfg, "micro_batch_size", 1) if pp_cfg else 1
        self._accumulate_steps = getattr(pp_cfg, "accumulate_steps", 1) if pp_cfg else 1

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _try_compiled(self, data, optimizer):
        """Delegate to the compiled 1F1B schedule when a 'pp' mesh axis is
        available and the wrapped model is a segmentable PipelineLayer —
        the real stage-partitioned pipeline (GenericPipeline1F1BTrainStep).
        Returns None when delegation isn't possible."""
        if getattr(self, "_compiled_failed", False):
            return None
        if not (hasattr(self._layers, "segment_parts")
                and getattr(self._layers, "loss_fn", None) is not None):
            return None
        from ...topology import get_default_mesh
        try:
            mesh = get_default_mesh()
        except Exception:
            return None
        if mesh is None or mesh.shape.get("pp", 1) <= 1:
            return None
        if getattr(self, "_compiled_step", None) is None:
            from ....parallel.pipeline_schedules import (
                GenericPipeline1F1BTrainStep)
            x, _ = data
            n_micro = max(self._accumulate_steps, mesh.shape["pp"])
            try:
                self._compiled_step = GenericPipeline1F1BTrainStep(
                    mesh, self._layers, optimizer, n_micro=n_micro,
                    example_input=x._value if isinstance(x, Tensor) else x)
            except Exception:
                # heterogeneous stage contract etc. — fall back loudly once
                import warnings
                warnings.warn(
                    "PipelineParallel: compiled 1F1B delegation unavailable "
                    "(stage activation contract not met); falling back to "
                    "micro-batch gradient accumulation WITHOUT stage "
                    "partitioning — every rank holds the full model. Use "
                    "paddle_tpu.parallel.Pipeline1F1BTrainStep directly for "
                    "the scalable path.")
                self._compiled_failed = True
                return None
        return self._compiled_step

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    loss_fn_idx=0):
        """reference pipeline_parallel.py:940 train_batch. With a 'pp' mesh
        axis and a segmentable PipelineLayer this delegates to the compiled
        1F1B schedule (real stage partitioning + P2P); otherwise it runs the
        micro-batch gradient-accumulation EMULATION — correct losses/grads,
        but no pipeline memory/compute partitioning."""
        if scaler is None:
            step = self._try_compiled(data, optimizer)
            if step is not None:
                loss = step(data)
                step.sync_to_model()
                if lr_scheduler is not None:
                    lr_scheduler.step()
                return loss
        x, y = data
        n_micro = self._accumulate_steps
        bs = x.shape[0]
        mbs = max(bs // n_micro, 1)
        n_micro = bs // mbs
        total_loss = None
        loss_fn = self._layers.loss_fn if hasattr(self._layers, "loss_fn") and \
            self._layers.loss_fn is not None else None
        for i in range(n_micro):
            xm = x[i * mbs:(i + 1) * mbs]
            ym = y[i * mbs:(i + 1) * mbs]
            out = self._layers.forward(xm)
            loss = loss_fn(out, ym) if loss_fn is not None else out
            scaled = loss * (1.0 / n_micro)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total_loss = scaled.detach() if total_loss is None \
                else total_loss + scaled.detach()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return total_loss

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers.forward(x)
        if compute_loss and getattr(self._layers, "loss_fn", None) is not None:
            return self._layers.loss_fn(out, y)
        return out
