"""meta_parallel (reference: fleet/meta_parallel/)."""
from __future__ import annotations

from .mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,  # noqa: F401
                        RowParallelLinear, ParallelCrossEntropy)
from .pp_layers import PipelineLayer, LayerDesc, SharedLayerDesc, SegmentLayers  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401

from ....nn.layer import Layer
from ....core.random import get_rng_state_tracker  # noqa: F401 (mpu/random.py parity)


class TensorParallel(Layer):
    """reference meta_parallel/tensor_parallel.py:28: wrapper that broadcasts
    non-distributed params across the mp group at init. On TPU the GSPMD
    sharding attached by the mp layers already pins placement; replicated
    params are consistent by construction (single controller), so this wrapper
    only preserves the interface."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)


class SegmentParallel(Layer):
    """reference meta_parallel/segment_parallel.py:26 — sequence split over the
    'sep' axis; activations are sharded on the sequence dim via constraints."""

    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)
