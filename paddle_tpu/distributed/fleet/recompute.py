"""Activation recomputation (reference: fleet/recompute/recompute.py —
RecomputeFunction :128, recompute() :463, recompute_sequential :630,
non-reentrant :327, RNG tracker replay :116).

TPU-native: eager mode uses the reentrant PyLayer pattern — forward runs under
no_grad (drops activations), backward re-runs forward with grad and routes
upstream grads through the fresh subgraph (param grads accumulate via the
tape's leaf accumulation, matching the reference). RNG state is snapshotted
and replayed so dropout masks match. Compiled train steps should instead use
``jax.checkpoint`` via paddle_tpu.parallel.compile helpers — same semantics,
handled by XLA rematerialization.
"""
from __future__ import annotations

from typing import Sequence

import jax

from ...core.tensor import Tensor
from ...core.dispatch import no_grad, is_grad_enabled
from ...core.autograd import GradNode, backward as tape_backward
from ...core import random as random_mod

__all__ = ["recompute", "recompute_sequential"]


class _RecomputeVjp:
    def __init__(self, function, args, kwargs, rng_state, n_outputs):
        self.function = function
        self.args = args
        self.kwargs = kwargs
        self.rng_state = rng_state
        self.n_outputs = n_outputs

    def __call__(self, cotangents):
        cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
        # replay RNG so dropout masks match the first forward
        saved = random_mod.get_rng_state()
        random_mod.set_rng_state(self.rng_state)
        try:
            detached = [a.detach() if isinstance(a, Tensor) else a for a in self.args]
            for d, a in zip(detached, self.args):
                if isinstance(a, Tensor):
                    d.stop_gradient = a.stop_gradient
            outs = self.function(*detached, **self.kwargs)
        finally:
            random_mod.set_rng_state(saved)
        outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)
        tensor_outs = [o for o in outs_t if isinstance(o, Tensor)]
        grads = [Tensor(c) for c in cts[: len(tensor_outs)]]
        tape_backward(tensor_outs, grads)
        in_grads = []
        for d in detached:
            if isinstance(d, Tensor) and d._grad is not None:
                in_grads.append(d._grad._value)
            elif isinstance(d, Tensor):
                import jax.numpy as jnp
                in_grads.append(jnp.zeros(d._value.shape, d._value.dtype))
        return tuple(in_grads)


def recompute(function, *args, **kwargs):
    """reference recompute.py:463."""
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    if not is_grad_enabled():
        return function(*args, **kwargs)
    rng_state = random_mod.get_rng_state() if preserve else None
    with no_grad():
        outs = function(*args, **kwargs)
    outs_t = outs if isinstance(outs, (tuple, list)) else (outs,)
    tensor_outs = [o for o in outs_t if isinstance(o, Tensor)]
    diff_inputs = [a for a in args if isinstance(a, Tensor) and not a.stop_gradient]
    if not diff_inputs and not any(not p.stop_gradient for p in _touched_params(function)):
        return outs
    tensor_inputs = [a for a in args if isinstance(a, Tensor)]
    node = GradNode(
        name="recompute",
        vjp_fn=_RecomputeVjp(function, args, kwargs, rng_state, len(tensor_outs)),
        inputs=tensor_inputs,
        out_avals=[(tuple(o.shape), o._value.dtype) for o in tensor_outs],
        multi=len(tensor_outs) > 1,
    )
    for k, o in enumerate(tensor_outs):
        o.stop_gradient = False
        o._grad_node = node
        o._out_index = k
        node.attach_output(k, o)
    return outs


def _touched_params(function):
    obj = getattr(function, "__self__", None)
    from ...nn.layer import Layer
    if isinstance(obj, Layer):
        return obj.parameters()
    if isinstance(function, Layer):
        return function.parameters()
    return []


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference recompute.py:630: chunk a Sequential into segments and
    recompute each."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if hasattr(functions, "_sub_layers"):
        fns = list(functions._sub_layers.values())
    else:
        fns = list(functions)
    n = len(fns)
    per = max(n // segments, 1)

    def seg_forward(lo, hi):
        def run(*inp):
            out = inp[0] if len(inp) == 1 else inp
            for f in fns[lo:hi]:
                out = f(out) if not isinstance(out, tuple) else f(*out)
            return out
        return run

    out = args
    i = 0
    while i < n:
        hi = min(i + per, n)
        run = seg_forward(i, hi)
        out = recompute(run, *(out if isinstance(out, tuple) else (out,)), **kwargs)
        i = hi
    return out
