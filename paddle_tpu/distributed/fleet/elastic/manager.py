"""ElasticManager (reference fleet/elastic/manager.py:125).

The reference watches an etcd prefix: each trainer registers
`/job/nodes/<host>` with a TTL lease; the manager reacts to node
join/leave by regenerating `PADDLE_TRAINER_ENDPOINTS`/rank env and
relaunching trainers (fault tolerance = relaunch from checkpoint).

TPU-native collapse: the coordination role is a pluggable `Store` —
`MemoryStore` (in-process, tests), `FileStore` (shared filesystem, the
single-host/NFS analog of etcd; heartbeat files with mtime as the TTL
lease). The manager's state machine matches the reference:

  register(host)           — lease registration
  watch() -> ElasticStatus — HOLD (stable) / CHANGE (membership moved)
                             / EXIT (below np_min after grace)
  rank_map()               — deterministic host → rank assignment
  on_change(cb)            — relaunch trigger (launch_gang restart hook)
"""
from __future__ import annotations

import dataclasses
import enum
import os
import time
from typing import Callable, Dict, List, Optional

__all__ = ["ElasticStatus", "ElasticManager", "FileStore", "MemoryStore"]


class ElasticStatus(enum.Enum):
    HOLD = "hold"        # membership stable
    CHANGE = "change"    # nodes joined/left within [np_min, np_max]
    EXIT = "exit"        # below np_min past the grace period


class MemoryStore:
    """In-process membership store (unit tests / single-controller).

    alive() orders by seniority (first registration time, then host name):
    every manager derives the SAME membership prefix from the same store
    state, so truncation at np_max is deterministic and joiners cannot
    evict senior members."""

    def __init__(self):
        self._beats: Dict[str, float] = {}
        self._first: Dict[str, float] = {}

    def heartbeat(self, host: str, ts: float = None, stale_after: float = None):
        now = ts if ts is not None else time.time()
        prev = self._beats.get(host)
        if prev is not None and stale_after is not None \
                and now - prev > stale_after:
            # the lease lapsed: a returning host re-enters as a JOINER —
            # it must not evict whoever replaced it (seniority resets)
            self._first.pop(host, None)
        self._beats[host] = now
        self._first.setdefault(host, now)

    def remove(self, host: str):
        self._beats.pop(host, None)
        self._first.pop(host, None)

    def alive(self, timeout: float) -> List[str]:
        now = time.time()
        live = [h for h, t in self._beats.items() if now - t <= timeout]
        return sorted(live, key=lambda h: (self._first.get(h, 0.0), h))


class FileStore:
    """Shared-filesystem membership store (the etcd-lease analog for
    single-host / NFS deployments): one heartbeat file per host, mtime is
    the lease."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, host):
        return os.path.join(self.root, f"node.{host.replace(':', '_')}")

    def heartbeat(self, host: str, ts: float = None, stale_after: float = None):
        p = self._path(host)
        now = ts if ts is not None else time.time()
        # preserve the first-registration time across beats (seniority key);
        # a lapsed lease resets it — the returning host re-enters as a joiner
        first = None
        try:
            if stale_after is None or now - os.path.getmtime(p) <= stale_after:
                first = open(p).read().split("\n")[1]
        except (OSError, IndexError):
            pass
        if first is None:
            first = repr(now)
        tmp = p + ".tmp"
        # atomic rename: a concurrent alive() must never read a truncated
        # host string (NFS deployment is this store's stated purpose)
        with open(tmp, "w") as f:
            f.write(f"{host}\n{first}")
        if ts is not None:
            os.utime(tmp, (ts, ts))
        os.replace(tmp, p)

    def remove(self, host: str):
        try:
            os.unlink(self._path(host))
        except FileNotFoundError:
            pass

    def alive(self, timeout: float) -> List[str]:
        """Live hosts ordered by (first registration, host) — the same
        deterministic prefix on every manager reading this store."""
        now = time.time()
        out = []
        for fn in os.listdir(self.root):
            if not fn.startswith("node."):
                continue
            p = os.path.join(self.root, fn)
            try:
                if now - os.path.getmtime(p) <= timeout:
                    parts = open(p).read().split("\n")
                    host = parts[0].strip()
                    first = float(parts[1]) if len(parts) > 1 else 0.0
                    if host:
                        out.append((first, host))
            except (OSError, ValueError):
                continue
        return [h for _, h in sorted(out)]


@dataclasses.dataclass
class _State:
    members: tuple = ()
    below_since: Optional[float] = None
    seen: bool = False          # first watch() is an observation, not a CHANGE


class ElasticManager:
    """reference manager.py:125: membership watch + rank regeneration.

    np_min/np_max: the elastic range (reference --np=min:max). A membership
    change inside the range returns CHANGE (caller relaunches with the new
    rank map, resuming from checkpoint); dropping below np_min starts the
    grace clock and returns EXIT once expired.
    """

    def __init__(self, store, np_min: int, np_max: int = None,
                 heartbeat_timeout: float = 10.0, grace_period: float = 30.0):
        self.store = store
        self.np_min = np_min
        self.np_max = np_max or np_min
        self.heartbeat_timeout = heartbeat_timeout
        self.grace_period = grace_period
        self._state = _State()
        self._callbacks: List[Callable] = []

    # -- lease/registration --------------------------------------------------
    def register(self, host: str):
        self.store.heartbeat(host, stale_after=self.heartbeat_timeout)

    def heartbeat(self, host: str):
        self.store.heartbeat(host, stale_after=self.heartbeat_timeout)

    def deregister(self, host: str):
        self.store.remove(host)

    # -- membership ----------------------------------------------------------
    def members(self) -> List[str]:
        """First np_max live hosts in the store's seniority order — pure
        function of store state, so every manager (including a freshly
        started one) derives the same membership, and a joiner can never
        evict a senior active worker at capacity."""
        return self.store.alive(self.heartbeat_timeout)[: self.np_max]

    def rank_map(self) -> Dict[str, int]:
        """Deterministic host→rank map (sorted order, reference re-rank)."""
        return {h: i for i, h in enumerate(self.members())}

    def endpoints(self) -> str:
        return ",".join(self.members())

    def on_change(self, cb: Callable):
        self._callbacks.append(cb)

    # -- the watch step ------------------------------------------------------
    def watch(self) -> ElasticStatus:
        cur = tuple(self.members())
        prev = self._state.members
        if len(cur) < self.np_min:
            if self._state.below_since is None:
                self._state.below_since = time.time()
            elif time.time() - self._state.below_since > self.grace_period:
                return ElasticStatus.EXIT
            self._state.members = cur
            return ElasticStatus.HOLD   # waiting out the grace period
        self._state.below_since = None
        if self._state.seen and cur != prev:
            self._state.members = cur
            for cb in self._callbacks:
                cb(self.rank_map())
            return ElasticStatus.CHANGE
        self._state.members = cur
        self._state.seen = True
        return ElasticStatus.HOLD
