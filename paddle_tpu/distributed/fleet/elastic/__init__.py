"""Elastic training manager (reference: python/paddle/distributed/fleet/
elastic/manager.py:125 ElasticManager — etcd membership watch, scale in/out,
rank-map regeneration, trainer relaunch)."""
from .manager import ElasticManager, ElasticStatus, FileStore, MemoryStore

__all__ = ["ElasticManager", "ElasticStatus", "FileStore", "MemoryStore"]
