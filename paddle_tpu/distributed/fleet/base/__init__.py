from .distributed_strategy import DistributedStrategy  # noqa: F401
