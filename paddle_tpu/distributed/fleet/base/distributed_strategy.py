"""DistributedStrategy (reference: python/paddle/distributed/fleet/base/
distributed_strategy.py backed by distributed_strategy.proto).

Plain-python config mirroring the proto fields the TPU build consumes:
hybrid_configs degrees, amp, recompute, sharding, pipeline, gradient_merge.
"""
from __future__ import annotations

__all__ = ["DistributedStrategy"]


class _Cfg(dict):
    __getattr__ = dict.get

    def __setattr__(self, k, v):
        self[k] = v


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
            "mp_configs": _Cfg(), "pp_configs": _Cfg(
                micro_batch_size=1, accumulate_steps=1, schedule_mode="1F1B"),
        }
        self.amp = False
        self.amp_configs = _Cfg(init_loss_scaling=65536.0, use_pure_fp16=False,
                                custom_white_list=[], custom_black_list=[])
        self.recompute = False
        self.recompute_configs = _Cfg(checkpoints=[])
        self.sharding = False
        self.sharding_configs = _Cfg(stage=1, degree=1)
        self.gradient_merge = False
        self.gradient_merge_configs = _Cfg(k_steps=1, avg=True)
        self.pipeline = False
        self.pipeline_configs = _Cfg(micro_batch_size=1, accumulate_steps=1)
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.last_comm_group_size_MB = 1
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.tensor_parallel = False
        self.tensor_parallel_configs = _Cfg(tensor_parallel_degree=1)

    def __setattr__(self, key, value):
        if key == "hybrid_configs" and hasattr(self, "hybrid_configs") \
                and isinstance(value, dict):
            merged = dict(self.__dict__["hybrid_configs"])
            merged.update(value)
            self.__dict__["hybrid_configs"] = merged
        else:
            self.__dict__[key] = value

    def __repr__(self):
        return f"DistributedStrategy(hybrid_configs={self.hybrid_configs})"
