"""Fleet hybrid-parallel orchestration (reference:
python/paddle/distributed/fleet/ — fleet.init, distributed_model model.py:33,
HybridParallelOptimizer, topology.py HybridCommunicateGroup).
"""
from __future__ import annotations

from .base.distributed_strategy import DistributedStrategy
from .fleet_api import (init, distributed_model, distributed_optimizer,
                        get_hybrid_communicate_group, worker_num, worker_index,
                        is_first_worker, barrier_worker, _get_fleet)
from . import meta_parallel
from .meta_parallel import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding, ParallelCrossEntropy,
                            PipelineLayer, LayerDesc, SharedLayerDesc,
                            TensorParallel, PipelineParallel)
from .recompute import recompute, recompute_sequential
from .utils import hybrid_parallel_util

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "meta_parallel", "ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy", "PipelineLayer",
           "LayerDesc", "SharedLayerDesc", "TensorParallel", "PipelineParallel",
           "recompute", "recompute_sequential"]
