"""Hybrid-parallel helpers (reference: fleet/utils/hybrid_parallel_util.py —
fused_allreduce_gradients :262, broadcast_*_parameters)."""
from __future__ import annotations

from ....core.tensor import Tensor
from ...communication.collectives import all_reduce, ReduceOp, broadcast

__all__ = ["fused_allreduce_gradients", "broadcast_mp_parameters",
           "broadcast_dp_parameters", "broadcast_sharding_parameters",
           "broadcast_sep_parameters"]


def fused_allreduce_gradients(parameter_list, hcg):
    """All-reduce grads over the dp group (XLA fuses the per-tensor collectives
    like the reference's fused buckets)."""
    group = hcg.get_data_parallel_group() if hcg is not None else None
    n = group.nranks if group is not None else 1
    if n <= 1:
        return
    for p in parameter_list:
        if p._grad is not None:
            all_reduce(p._grad, op=ReduceOp.SUM, group=group)
            p._grad._set_value(p._grad._value / n)


def broadcast_mp_parameters(model, hcg):
    _broadcast_params(model, hcg.get_model_parallel_group())


def broadcast_dp_parameters(model, hcg):
    _broadcast_params(model, hcg.get_data_parallel_group())


def broadcast_sharding_parameters(model, hcg):
    _broadcast_params(model, hcg.get_sharding_parallel_group())


def broadcast_sep_parameters(model, hcg):
    _broadcast_params(model, hcg.get_sep_parallel_group())


def _broadcast_params(model, group):
    if group is None or group.nranks <= 1:
        return
    for p in model.parameters():
        broadcast(p, src=group.ranks[0], group=group)
