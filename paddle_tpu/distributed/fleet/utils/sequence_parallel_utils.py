"""Megatron sequence parallelism (reference: fleet/utils/
sequence_parallel_utils.py — ScatterOp/GatherOp/AllGatherOp/ReduceScatterOp
PyLayers :85-250, ColumnSequenceParallelLinear / RowSequenceParallelLinear
:336-564, overlap variant SPInnerOverlapLinear :257).

TPU-native: in GSPMD mode the scatter/gather pair is a pair of sharding
constraints on the sequence dim (XLA inserts all-gather/reduce-scatter and
overlaps them with the matmuls — the hand-written SPInnerOverlapLinear
overlap comes free). In shard_map mode the explicit collectives are used.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....core.dispatch import op_call
from ....nn.layer import Layer
from ....nn import functional as F_nn
from ..meta_parallel.mp_layers import mp_axis_in_scope, constrain, shard_param

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "mark_as_sequence_parallel_parameter",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "SPInnerOverlapLinear",
           "create_fused_allreduce_gradient_hooks"]


class ScatterOp:
    """Split activations along sequence dim across 'mp' (reference :85)."""

    @staticmethod
    def apply(x, axis=0):
        if mp_axis_in_scope("mp"):
            def impl(v):
                n = jax.lax.psum(1, "mp")
                r = jax.lax.axis_index("mp")
                per = v.shape[axis] // n
                return jax.lax.dynamic_slice_in_dim(v, r * per, per, axis)
            return op_call("sp_scatter", impl, x)
        return constrain(x, *(["mp" if i == axis else None for i in range(x.ndim)]))


class GatherOp:
    """Inverse of ScatterOp (reference :~120)."""

    @staticmethod
    def apply(x, axis=0):
        if mp_axis_in_scope("mp"):
            def impl(v):
                g = jax.lax.all_gather(v, "mp")  # [n, ...]
                return jnp.concatenate([g[i] for i in range(g.shape[0])], axis=axis)
            return op_call("sp_gather", impl, x)
        return constrain(x, *([None] * x.ndim))


class AllGatherOp:
    """all-gather along sequence in fwd, reduce-scatter in bwd (reference :176)."""

    @staticmethod
    def apply(x, axis=0):
        return GatherOp.apply(x, axis)


class ReduceScatterOp:
    @staticmethod
    def apply(x, axis=0):
        if mp_axis_in_scope("mp"):
            def impl(v):
                return jax.lax.psum_scatter(v, "mp", scatter_dimension=axis, tiled=True)
            return op_call("sp_reduce_scatter", impl, x)
        return ScatterOp.apply(x, axis)


def mark_as_sequence_parallel_parameter(param):
    param.sequence_parallel = True


class ColumnSequenceParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter((in_features, out_features), attr=weight_attr)
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias in (True, None) else None
        shard_param(self.weight, (None, "mp"))

    def forward(self, x):
        # input is sequence-sharded; gather sequence, compute column shard
        full = AllGatherOp.apply(x, axis=0 if x.ndim == 3 else 0)
        out = F_nn.linear(full, self.weight, self.bias)
        return constrain(out, *([None] * (out.ndim - 1)), "mp")


class RowSequenceParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.weight = self.create_parameter((in_features, out_features), attr=weight_attr)
        self.bias = self.create_parameter((out_features,), is_bias=True) if has_bias else None
        shard_param(self.weight, ("mp", None))

    def forward(self, x):
        out = F_nn.linear(x, self.weight, None)
        out = ReduceScatterOp.apply(out, axis=0)
        if self.bias is not None:
            out = out + self.bias
        return out


def _ring_allgather_matmul(x_local, w, axis="mp"):
    """Overlapped sequence all-gather × column matmul: each ring step
    matmuls the sequence chunk it holds while ppermuting the next chunk in —
    the TPU analog of SPInnerOverlapLinear's chunked comm/compute pipeline
    (reference :257); XLA's latency-hiding scheduler overlaps the ppermute
    with the dot.

    x_local: [S_local, ...rest, H_in]; w: [H_in, out_local].
    Returns [S_global, ...rest, out_local] (sequence-major).
    """
    n = jax.lax.psum(1, axis)
    r = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    S_l = x_local.shape[0]

    def body(i, carry):
        chunk, out = carry
        part = jnp.tensordot(chunk, w, axes=([-1], [0]))
        # chunk i arrived from rank (r - i) mod n → its global offset
        src = jnp.mod(r - i, n)
        out = jax.lax.dynamic_update_slice_in_dim(out, part, src * S_l, 0)
        chunk = jax.lax.ppermute(chunk, axis, perm)
        return chunk, out

    out0 = jnp.zeros((S_l * n,) + x_local.shape[1:-1] + (w.shape[-1],),
                     x_local.dtype)
    # align vma types: the zeros carry must be mp-varying like the chunks
    from ....parallel.pipeline_schedules import _vary
    out0 = _vary(out0, ("mp",))
    x_local = _vary(x_local, ("mp",))
    _, out = jax.lax.fori_loop(0, n, body, (x_local, out0))
    return out


class SPInnerOverlapLinear(Layer):
    """ColumnSequenceParallelLinear with comm/compute overlap (reference
    SPInnerOverlapLinear :257): the sequence all-gather is decomposed into a
    ppermute ring whose chunks matmul as they arrive."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, fuse_matmul_bias=False, mp_group=None,
                 name=None):
        super().__init__()
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias in (True, None) else None
        shard_param(self.weight, (None, "mp"))

    def forward(self, x):
        if mp_axis_in_scope("mp"):
            def impl(v, w, *b):
                out = _ring_allgather_matmul(v, w, "mp")
                if b:
                    out = out + b[0]
                return out
            args = [x, self.weight] + ([self.bias] if self.bias is not None
                                       else [])
            return op_call("sp_overlap_linear", impl, *args)
        # GSPMD mode: constraints; XLA fuses + overlaps the all-gather itself
        full = AllGatherOp.apply(x, axis=0)
        out = F_nn.linear(full, self.weight, self.bias)
        return constrain(out, *([None] * (out.ndim - 1)), "mp")


def create_fused_allreduce_gradient_hooks(model, accumulation_steps=1):
    return []
