"""fleet.init / distributed_model / distributed_optimizer (reference:
python/paddle/distributed/fleet/fleet.py init, model.py:33 distributed_model,
optimizer.py distributed_optimizer → HybridParallelOptimizer
hybrid_parallel_optimizer.py:275).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax

from .base.distributed_strategy import DistributedStrategy
from ..topology import CommunicateTopology, HybridCommunicateGroup
from ..env import init_parallel_env, get_rank, get_world_size

_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=False, strategy: Optional[DistributedStrategy] = None,
         log_level="INFO"):
    """fleet.init parity: builds the hybrid topology + global mesh from
    hybrid_configs degrees."""
    strategy = strategy or DistributedStrategy()
    init_parallel_env()
    hc = strategy.hybrid_configs
    dp = int(hc.get("dp_degree", 1))
    mp = int(hc.get("mp_degree", 1))
    pp = int(hc.get("pp_degree", 1))
    sharding = int(hc.get("sharding_degree", 1))
    sep = int(hc.get("sep_degree", 1))
    n_dev = jax.device_count()
    declared = dp * mp * pp * sharding * sep
    if declared != n_dev and declared == 1:
        dp = n_dev  # default: pure DP over all devices
    elif declared != n_dev:
        # honor declared degrees on a subset/superset — scale dp to fit
        rest = mp * pp * sharding * sep
        if n_dev % rest == 0:
            dp = n_dev // rest
        else:
            raise ValueError(
                f"hybrid degrees {hc} don't tile {n_dev} devices")
    topo = CommunicateTopology(dims=(dp, pp, sharding, sep, mp))
    hcg = HybridCommunicateGroup(topo)
    _state.update(strategy=strategy, hcg=hcg, initialized=True)
    return


def _get_fleet():
    return _state


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    if _state["hcg"] is None:
        init(is_collective=True)
    return _state["hcg"]


def fleet_initialized():
    return _state["initialized"]


def worker_num():
    return get_world_size()


def worker_index():
    return get_rank()


def is_first_worker():
    return get_rank() == 0


def barrier_worker():
    from ..communication.group import barrier
    barrier()


def distributed_model(model):
    """reference model.py:33: wrap by parallelism mode."""
    hcg = get_hybrid_communicate_group()
    from .meta_parallel import (PipelineLayer, PipelineParallel, TensorParallel)
    from ..parallel import DataParallel
    if hcg.get_pipe_parallel_world_size() > 1 and isinstance(model, PipelineLayer):
        return PipelineParallel(model, hcg, _state["strategy"])
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, _state["strategy"])
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model)
    return model


def distributed_optimizer(optimizer, strategy=None):
    from .hybrid_optimizer import HybridParallelOptimizer
    hcg = get_hybrid_communicate_group()
    return HybridParallelOptimizer(optimizer, hcg, strategy or _state["strategy"])
