"""Parallel-config auto-tuner (reference: python/paddle/distributed/
auto_tuner/tuner.py:21 AutoTuner, search.py:48 GridSearch, prune.py,
memory_cost_model.py, recorder.py HistoryRecorder)."""
from .tuner import AutoTuner, TrialResult
from .search import GridSearch, candidate_configs
from .prune import prune_by_memory, estimate_bytes_per_device
from .recorder import HistoryRecorder

__all__ = ["AutoTuner", "TrialResult", "GridSearch", "candidate_configs",
           "prune_by_memory", "estimate_bytes_per_device", "HistoryRecorder"]
