"""Search algorithms (reference auto_tuner/search.py:48 GridSearch)."""
from __future__ import annotations

import itertools

__all__ = ["candidate_configs", "GridSearch"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def candidate_configs(n_devices, *, n_layers=None, n_heads=None,
                      global_batch=None, micro_candidates=(1, 2, 4, 8),
                      zero_stages=(0, 1, 2, 3), remat=(False, True)):
    """Enumerate {dp, mp, pp, n_micro, zero_stage, remat} with
    dp*mp*pp == n_devices and basic divisibility constraints
    (reference search.py's dims construction)."""
    out = []
    for dp in _divisors(n_devices):
        for mp in _divisors(n_devices // dp):
            pp = n_devices // dp // mp
            if n_layers is not None and pp > 1 and n_layers % pp != 0:
                continue
            if n_heads is not None and mp > 1 and n_heads % mp != 0:
                continue
            for n_micro in micro_candidates:
                if pp > 1 and n_micro < pp:
                    continue           # pipeline needs >= pp microbatches
                if global_batch is not None and global_batch % (dp * n_micro):
                    continue
                for zs in zero_stages:
                    if zs > 0 and dp == 1:
                        continue       # ZeRO shards over dp
                    if zs > 0 and pp > 1:
                        continue       # one sharding engine at a time here
                    for rm in remat:
                        out.append({"dp": dp, "mp": mp, "pp": pp,
                                    "n_micro": n_micro, "zero_stage": zs,
                                    "remat": rm})
    return out


class GridSearch:
    """Exhaustive walk over the (pruned) candidate list."""

    def __init__(self, candidates):
        self.candidates = list(candidates)
        self._i = 0

    def __iter__(self):
        return iter(self.candidates)

    def next_config(self):
        if self._i >= len(self.candidates):
            return None
        c = self.candidates[self._i]
        self._i += 1
        return c
