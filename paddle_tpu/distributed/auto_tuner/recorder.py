"""Trial history CSV (reference auto_tuner/recorder.py HistoryRecorder)."""
from __future__ import annotations

import csv
import os

__all__ = ["HistoryRecorder"]


class HistoryRecorder:
    FIELDS = ["dp", "mp", "pp", "n_micro", "zero_stage", "remat",
              "status", "time_per_step", "tokens_per_sec", "error"]

    def __init__(self, path=None):
        self.path = path
        self.history = []

    def add(self, cfg, status, time_per_step=None, tokens_per_sec=None,
            error=None):
        row = dict(cfg)
        row.update({"status": status, "time_per_step": time_per_step,
                    "tokens_per_sec": tokens_per_sec, "error": error})
        self.history.append(row)
        if self.path:
            exists = os.path.exists(self.path)
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a", newline="") as f:
                w = csv.DictWriter(f, fieldnames=self.FIELDS)
                if not exists:
                    w.writeheader()
                w.writerow({k: row.get(k) for k in self.FIELDS})

    def best(self):
        ok = [r for r in self.history if r["status"] == "ok"
              and r["tokens_per_sec"]]
        return max(ok, key=lambda r: r["tokens_per_sec"]) if ok else None
