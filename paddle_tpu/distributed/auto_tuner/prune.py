"""Memory-model pruning (reference auto_tuner/prune.py +
memory_cost_model.py): estimate per-device bytes for a candidate config and
drop candidates that cannot fit before paying for a trial."""
from __future__ import annotations

__all__ = ["estimate_bytes_per_device", "prune_by_memory"]


def estimate_bytes_per_device(cfg, *, n_params, hidden, n_layers, seq_len,
                              micro_batch_size, param_bytes=4,
                              grad_bytes=4, opt_bytes=8,
                              act_bytes_per_token_layer=None):
    """Coarse analytical model (memory_cost_model.py analog).

    params/grads shard over mp (block weights) and zero-stage>=1 shards
    optimizer state over dp; stage 2 also grads; stage 3 also params.
    Activations: micro_batch tokens × layers-resident. remat bounds the
    resident layer count to 1 block (+ schedule depth under pp).
    """
    dp, mp, pp = cfg["dp"], cfg["mp"], cfg["pp"]
    zs, remat = cfg["zero_stage"], cfg["remat"]
    shard_model = mp * pp
    p_local = n_params / shard_model
    param_b = p_local * param_bytes / (dp if zs >= 3 else 1)
    grad_b = p_local * grad_bytes / (dp if zs >= 2 else 1)
    opt_b = p_local * opt_bytes / (dp if zs >= 1 else 1)
    if act_bytes_per_token_layer is None:
        # ~20 live fp32 values per token per layer in a transformer block
        act_bytes_per_token_layer = 20 * hidden * 4
    layers_resident = (1 if remat else n_layers / pp)
    depth = min(cfg["n_micro"], 2 * (pp - 1) + 1) if pp > 1 else 1
    act_b = (micro_batch_size * seq_len * act_bytes_per_token_layer
             * layers_resident * depth)
    return param_b + grad_b + opt_b + act_b


def prune_by_memory(candidates, hbm_bytes, **model_kw):
    """Keep candidates whose estimate fits in hbm_bytes (with 10% headroom).
    Returns (kept, pruned_with_estimates)."""
    kept, pruned = [], []
    budget = hbm_bytes * 0.9
    for c in candidates:
        est = estimate_bytes_per_device(c, **model_kw)
        (kept if est <= budget else pruned).append((c, est))
    return [c for c, _ in kept], pruned
