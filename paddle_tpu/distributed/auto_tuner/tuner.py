"""AutoTuner (reference auto_tuner/tuner.py:21): search the parallel-config
space with memory pruning + short timed trials, record history, return the
best config.

TPU-native: a trial builds the candidate's compiled train step on the
available device mesh (virtual CPU mesh in tests — the reference launches
subprocess trial jobs; one-process mesh trials are the XLA analog) and
times a few steps.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import numpy as np

from .search import GridSearch, candidate_configs
from .prune import prune_by_memory
from .recorder import HistoryRecorder

__all__ = ["AutoTuner", "TrialResult"]


@dataclasses.dataclass
class TrialResult:
    config: dict
    time_per_step: float
    tokens_per_sec: float


class AutoTuner:
    def __init__(self, model_config=None, *, n_devices=None, global_batch=8,
                 seq_len=16, history_csv: Optional[str] = None,
                 hbm_bytes: Optional[int] = None,
                 trial_fn: Optional[Callable] = None):
        """model_config: LlamaConfig for the built-in llama trial runner, or
        pass trial_fn(cfg, global_batch, seq_len, steps=, warmup=) ->
        seconds_per_step (keyword args steps/warmup are always passed)."""
        import jax
        self.model_config = model_config
        self.n_devices = n_devices or jax.device_count()
        self.global_batch = global_batch
        self.seq_len = seq_len
        self.recorder = HistoryRecorder(history_csv)
        self.hbm_bytes = hbm_bytes
        self.trial_fn = trial_fn or self._llama_trial

    # -- candidate generation + pruning ------------------------------------
    def candidates(self, **kw):
        c = self.model_config
        cands = candidate_configs(
            self.n_devices,
            n_layers=getattr(c, "num_hidden_layers", None),
            n_heads=getattr(c, "num_attention_heads", None),
            global_batch=self.global_batch, **kw)
        if self.hbm_bytes and c is not None:
            n_params = (c.vocab_size * c.hidden_size * 2
                        + c.num_hidden_layers
                        * (4 * c.hidden_size ** 2
                           + 3 * c.hidden_size * c.intermediate_size))
            cands, _ = prune_by_memory(
                cands, self.hbm_bytes, n_params=n_params,
                hidden=c.hidden_size, n_layers=c.num_hidden_layers,
                seq_len=self.seq_len,
                micro_batch_size=max(1, self.global_batch
                                     // max(1, self.n_devices)))
        return cands

    # -- the built-in llama trial ------------------------------------------
    def _llama_trial(self, cfg, global_batch, seq_len, steps=3, warmup=1):
        import jax
        import jax.numpy as jnp
        from ...models.llama import build_functional_llama, llama_microbatch_fns, \
            llama_block_specs
        from ...parallel.pipeline_schedules import Pipeline1F1BTrainStep
        from ...parallel.sharded import ShardedTrainStep
        from ..topology import build_mesh
        from ... import optimizer

        c = self.model_config
        devs = jax.devices()[: self.n_devices]
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, c.vocab_size,
                                       (global_batch, seq_len)).astype(np.int32))
        batch = (ids, ids)
        opt = optimizer.AdamW(learning_rate=1e-3, parameters=[])

        if cfg["pp"] > 1 or cfg["mp"] > 1:
            axes = {k: v for k, v in (("dp", cfg["dp"]), ("pp", cfg["pp"]),
                                      ("mp", cfg["mp"])) if v > 1 or k == "pp"}
            axes.setdefault("dp", cfg["dp"])
            mesh = build_mesh(axes, devices=devs)
            mp_axis = "mp" if cfg["mp"] > 1 else None
            ep, bp, hp, *_ = build_functional_llama(c, n_micro=cfg["n_micro"],
                                                    mp_axis=mp_axis)
            ea, ba, hl = llama_microbatch_fns(c, mp_axis=mp_axis)
            specs = llama_block_specs("mp") if mp_axis else None
            step = Pipeline1F1BTrainStep(
                mesh, ea, ba, hl, ep, bp, hp, opt, n_micro=cfg["n_micro"],
                block_specs=specs, remat_stage=cfg["remat"])
        else:
            mesh = build_mesh({"dp": cfg["dp"]}, devices=devs[: cfg["dp"]])
            ep, bp, hp, ea, ba, hl = build_functional_llama(c, n_micro=1)

            def loss_fn(params, batch):
                ep_, bp_, hp_ = params
                x = ea(ep_, batch)[0]
                bfn = jax.checkpoint(ba) if cfg["remat"] else ba
                def body(a, lp):
                    return bfn(lp, a), None
                x, _ = jax.lax.scan(body, x, bp_)
                return hl(hp_, x[None], batch)

            step = ShardedTrainStep(mesh, loss_fn, (ep, bp, hp), opt,
                                    stage=max(cfg["zero_stage"], 0), axis="dp")

        # always run >=1 untimed step so compile cost never lands in the
        # timed loop (and `loss` is defined even when warmup=0)
        for _ in range(max(warmup, 1)):
            loss = step(batch)
        jax.block_until_ready(loss._value if hasattr(loss, "_value") else loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(batch)
        jax.block_until_ready(loss._value if hasattr(loss, "_value") else loss)
        return (time.perf_counter() - t0) / steps

    # -- main loop ----------------------------------------------------------
    def tune(self, max_trials=None, steps=3, warmup=1, **cand_kw):
        """Run trials over the (pruned) grid; returns the best TrialResult."""
        cands = self.candidates(**cand_kw)
        if max_trials:
            cands = cands[:max_trials]
        search = GridSearch(cands)
        best = None
        for cfg in search:
            try:
                tps_step = self.trial_fn(cfg, self.global_batch, self.seq_len,
                                         steps=steps, warmup=warmup)
                tokens = self.global_batch * self.seq_len / tps_step
                self.recorder.add(cfg, "ok", time_per_step=tps_step,
                                  tokens_per_sec=tokens)
                if best is None or tokens > best.tokens_per_sec:
                    best = TrialResult(cfg, tps_step, tokens)
            except Exception as e:  # noqa: BLE001 — a failing candidate is
                # data (OOM/invalid), not a tuner crash (reference prune-on-
                # fail semantics)
                self.recorder.add(cfg, "fail", error=f"{type(e).__name__}: {e}")
        return best
