"""Collective communication API (reference:
python/paddle/distributed/communication/*.py → ProcessGroupNCCL
process_group_nccl.h:37; AllReduce :105).

TPU-native dual dispatch (SURVEY.md §2.3): the same `dist.all_reduce(t)` call

1. **traced** (inside a shard_map'ped/pjit'ted step fn): lowers directly to the
   XLA collective (`lax.psum` / `all_gather` / `ppermute`) over the group's
   mesh axis — compiled, fused, and overlap-scheduled by XLA over ICI.
2. **eager on a sharded global array**: wraps the collective in a cached
   shard_map jit over the array's mesh (eager-mode collectives analog).
3. **eager single-participant**: identity (world_size 1).

ReduceOp matches the reference's enum (communication/reduce.py).
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .group import Group, get_group

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_gather_object", "reduce",
           "broadcast", "scatter", "reduce_scatter", "all_to_all",
           "all_to_all_single", "send", "recv", "isend", "irecv",
           "batch_isend_irecv", "P2POp", "gather"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _is_traced(v):
    return isinstance(v, jax.core.Tracer)


def _axis(group: Optional[Group]) -> str:
    if group is not None and group.axis_name:
        return group.axis_name
    return "dp"


def _reduce_traced(v, op, axis):
    if op == ReduceOp.SUM:
        return jax.lax.psum(v, axis)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(v, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(v, axis)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(v, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(jax.lax.psum(jnp.log(v), axis))
    raise ValueError(f"unsupported reduce op {op}")


def _mesh_of(v) -> Optional[Mesh]:
    try:
        sh = v.sharding
        if isinstance(sh, NamedSharding):
            return sh.mesh
    except Exception:
        pass
    return None


@functools.lru_cache(maxsize=256)
def _eager_allreduce_fn(mesh, spec, op, axis):
    from jax import shard_map
    def body(x):
        return _reduce_traced(x, op, axis)
    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
    return jax.jit(fn)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In place on the Tensor (reference semantics)."""
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        out = _reduce_traced(v, op, axis)
        tensor._set_value(out)
        return tensor
    mesh = _mesh_of(v)
    if mesh is not None and axis in mesh.axis_names and mesh.shape[axis] > 1:
        spec = v.sharding.spec
        out = _eager_allreduce_fn(mesh, spec, op, axis)(v)
        tensor._set_value(out)
        return tensor
    # single participant: identity
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # XLA collectives are all-to-all-symmetric; reduce == all_reduce with the
    # result visible on every participant (superset of the contract)
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        gathered = jax.lax.all_gather(v, axis)  # [n, ...]
        n = gathered.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(gathered[i]) for i in range(n))
        return Tensor(gathered)
    # eager: single participant
    if isinstance(tensor_list, list):
        tensor_list.append(Tensor(v))
    return Tensor(v[None])


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def broadcast(tensor, src=0, group=None, sync_op=True):
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        n = jax.lax.psum(1, axis)
        src_local = get_group(0).get_group_rank(src) if group is None else \
            group.get_group_rank(src)
        src_local = max(src_local, 0)
        # select src's shard on every member: gather then index
        gathered = jax.lax.all_gather(v, axis)
        tensor._set_value(gathered[src_local])
        return tensor
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        idx = 0 if group is None else max(group.rank, 0)
        tensor._set_value(tensor_list[idx]._value)
    return tensor


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis(group)
    if isinstance(tensor_list, (list, tuple)):
        stacked = jnp.stack([t._value for t in tensor_list])
    else:
        stacked = tensor_list._value
    if _is_traced(stacked):
        out = jax.lax.psum_scatter(stacked, axis, scatter_dimension=0, tiled=False)
        tensor._set_value(out)
        return tensor
    tensor._set_value(stacked.sum(0) if op == ReduceOp.SUM else stacked.max(0))
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = _axis(group)
    vals = [t._value for t in in_tensor_list]
    if vals and _is_traced(vals[0]):
        stacked = jnp.stack(vals)  # [n, ...] one slot per peer
        out = jax.lax.all_to_all(stacked, axis, split_axis=0, concat_axis=0, tiled=False)
        for i in range(out.shape[0]):
            out_tensor_list.append(Tensor(out[i]))
        return
    out_tensor_list.extend(Tensor(v) for v in vals)


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    v = in_tensor._value
    axis = _axis(group)
    if _is_traced(v):
        out = jax.lax.all_to_all(v, axis, split_axis=0, concat_axis=0, tiled=True)
        out_tensor._set_value(out)
        return out_tensor
    out_tensor._set_value(v)
    return out_tensor


def send(tensor, dst=0, group=None, sync_op=True):
    """In-graph p2p via ppermute (pipeline stages); eager send between
    processes is expressed through the pipeline schedule's compiled steps on
    TPU (no raw NCCL-like eager p2p)."""
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        n = 1
        perm = None  # ring shift to neighbor: dst relative
        return Tensor(jax.lax.ppermute(v, axis, _ring_perm(axis, +1)))
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        return Tensor(jax.lax.ppermute(v, axis, _ring_perm(axis, +1)))
    return tensor


def _ring_perm(axis, shift):
    # resolved at trace time using the bound mesh
    from ..topology import get_default_mesh
    mesh = get_default_mesh()
    n = mesh.shape[axis] if axis in mesh.axis_names else 1
    return [(i, (i + shift) % n) for i in range(n)]


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    class _Task:
        def wait(self):
            pass
    return _Task()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    class _Task:
        def wait(self):
            pass
    return _Task()


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    if gather_list is not None:
        gather_list.append(Tensor(tensor._value))
    return tensor
