"""Collective communication API (reference:
python/paddle/distributed/communication/*.py → ProcessGroupNCCL
process_group_nccl.h:37; AllReduce :105).

TPU-native dual dispatch (SURVEY.md §2.3): the same `dist.all_reduce(t)` call

1. **traced** (inside a shard_map'ped/pjit'ted step fn): lowers directly to the
   XLA collective (`lax.psum` / `all_gather` / `ppermute`) over the group's
   mesh axis — compiled, fused, and overlap-scheduled by XLA over ICI.
2. **eager on a sharded global array**: wraps the collective in a cached
   shard_map jit over the array's mesh (eager-mode collectives analog).
3. **eager single-participant**: identity (world_size 1).

ReduceOp matches the reference's enum (communication/reduce.py).
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .group import Group, get_group

__all__ = ["ReduceOp", "all_reduce", "all_gather", "all_gather_object", "reduce",
           "broadcast", "scatter", "reduce_scatter", "all_to_all",
           "all_to_all_single", "send", "recv", "isend", "irecv",
           "batch_isend_irecv", "P2POp", "gather"]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _is_traced(v):
    return isinstance(v, jax.core.Tracer)


def _axis(group: Optional[Group]) -> str:
    if group is not None and group.axis_name:
        return group.axis_name
    return "dp"


def _reduce_traced(v, op, axis):
    if op == ReduceOp.SUM:
        return jax.lax.psum(v, axis)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(v, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(v, axis)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(v, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(jax.lax.psum(jnp.log(v), axis))
    raise ValueError(f"unsupported reduce op {op}")


def _mesh_of(v) -> Optional[Mesh]:
    try:
        sh = v.sharding
        if isinstance(sh, NamedSharding):
            return sh.mesh
    except Exception:
        pass
    return None


@functools.lru_cache(maxsize=256)
def _eager_allreduce_fn(mesh, spec, op, axis):
    from jax import shard_map
    def body(x):
        return _reduce_traced(x, op, axis)
    fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
    return jax.jit(fn)


@functools.lru_cache(maxsize=256)
def _eager_crossproc_allreduce_fn(shape, dtype, op):
    """Cross-process all-reduce for eager LOCAL arrays (the DataParallel
    grad-hook path): stack the per-process values on a process mesh, psum
    inside shard_map, read back this process's shard.  The pre-round-5
    fallback silently returned the local value — half-magnitude DP grads
    that no error ever surfaced (caught by the hapi distributed-fit
    loss-curve test)."""
    import numpy as _np
    from jax import shard_map
    n = jax.process_count()
    # ONE device per process: hosts with several local chips would otherwise
    # make the axis larger than the shard count we stack below
    per_proc = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    devs = [per_proc[i] for i in sorted(per_proc)]
    mesh = Mesh(_np.array(devs), ("_ar",))

    def body(x):
        return _reduce_traced(x, op, "_ar")
    fn = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("_ar"),),
                           out_specs=P("_ar")))

    def run(v):
        g = jax.make_array_from_single_device_arrays(
            (n,) + shape, NamedSharding(mesh, P("_ar")),
            [jax.device_put(v[None], per_proc[jax.process_index()])])
        out = fn(g)
        return out.addressable_data(0)[0]
    return run


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In place on the Tensor (reference semantics)."""
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        out = _reduce_traced(v, op, axis)
        tensor._set_value(out)
        return tensor
    mesh = _mesh_of(v)
    if mesh is not None and axis in mesh.axis_names and mesh.shape[axis] > 1:
        spec = v.sharding.spec
        out = _eager_allreduce_fn(mesh, spec, op, axis)(v)
        tensor._set_value(out)
        return tensor
    if jax.process_count() > 1:
        g = group if group is not None else get_group(0)
        if getattr(g, "nranks", jax.process_count()) not in (
                0, jax.process_count()):
            raise NotImplementedError(
                "eager all_reduce over a strict sub-group of processes is "
                "not supported for local arrays — shard the tensor over a "
                "mesh that names the group axis")
        out = _eager_crossproc_allreduce_fn(v.shape, str(v.dtype), op)(v)
        tensor._set_value(out)
        return tensor
    # single participant: identity
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # XLA collectives are all-to-all-symmetric; reduce == all_reduce with the
    # result visible on every participant (superset of the contract)
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        gathered = jax.lax.all_gather(v, axis)  # [n, ...]
        n = gathered.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(gathered[i]) for i in range(n))
        return Tensor(gathered)
    # eager: single participant
    if isinstance(tensor_list, list):
        tensor_list.append(Tensor(v))
    return Tensor(v[None])


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def broadcast(tensor, src=0, group=None, sync_op=True):
    v = tensor._value
    axis = _axis(group)
    if _is_traced(v):
        n = jax.lax.psum(1, axis)
        src_local = get_group(0).get_group_rank(src) if group is None else \
            group.get_group_rank(src)
        src_local = max(src_local, 0)
        # select src's shard on every member: gather then index
        gathered = jax.lax.all_gather(v, axis)
        tensor._set_value(gathered[src_local])
        return tensor
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        idx = 0 if group is None else max(group.rank, 0)
        tensor._set_value(tensor_list[idx]._value)
    return tensor


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = _axis(group)
    if isinstance(tensor_list, (list, tuple)):
        stacked = jnp.stack([t._value for t in tensor_list])
    else:
        stacked = tensor_list._value
    if _is_traced(stacked):
        out = jax.lax.psum_scatter(stacked, axis, scatter_dimension=0, tiled=False)
        tensor._set_value(out)
        return tensor
    tensor._set_value(stacked.sum(0) if op == ReduceOp.SUM else stacked.max(0))
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = _axis(group)
    vals = [t._value for t in in_tensor_list]
    if vals and _is_traced(vals[0]):
        stacked = jnp.stack(vals)  # [n, ...] one slot per peer
        out = jax.lax.all_to_all(stacked, axis, split_axis=0, concat_axis=0, tiled=False)
        for i in range(out.shape[0]):
            out_tensor_list.append(Tensor(out[i]))
        return
    out_tensor_list.extend(Tensor(v) for v in vals)


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    v = in_tensor._value
    axis = _axis(group)
    if _is_traced(v):
        out = jax.lax.all_to_all(v, axis, split_axis=0, concat_axis=0, tiled=True)
        out_tensor._set_value(out)
        return out_tensor
    out_tensor._set_value(v)
    return out_tensor


def _p2p_shift(group, peer, kind):
    """Uniform cyclic shift realizing this rank's p2p request.

    SPMD semantics (reference send.py/recv.py are MPI-style p2p; XLA has no
    point-to-point — ppermute is the primitive): `send(t, dst)` on rank r
    and the matching `recv(t, src=r)` on rank dst both lower to the SAME
    ppermute with shift = (dst - r) mod n, so the per-rank branches of a
    multi-controller program stay identical XLA programs.  Within one
    traced program (shard_map over a virtual axis) the single shift
    generalizes the pair to a uniform cyclic rotation — non-uniform
    patterns need lax.ppermute directly."""
    g = group if group is not None else get_group(0)
    n = max(g.nranks, 1)
    me = g.rank
    if me < 0:
        me = 0
    ranks = list(getattr(g, "ranks", []) or [])
    if ranks and ranks != sorted(ranks):
        # The ppermute perm addresses MESH-AXIS indices; the shift below is
        # computed from group-local positions. These only coincide when the
        # group's ranks are listed in axis order — a permuted order (e.g.
        # new_group([1, 0])) would pass the axis-size check yet silently
        # deliver to the wrong peer.
        raise ValueError(
            f"p2p group ranks {ranks} are not in ascending (mesh-axis) "
            "order; group-local shifts would address the wrong axis "
            "members. Create the group with sorted ranks, or use "
            "lax.ppermute with an explicit perm.")
    peer_local = g.get_group_rank(peer)
    if peer_local < 0:
        raise ValueError(
            f"p2p peer {peer} is not a member of group ranks {g.ranks}")
    if kind == "send":
        return (peer_local - me) % n, n
    return (me - peer_local) % n, n


@functools.lru_cache(maxsize=256)
def _eager_ppermute_fn(mesh, spec, axis, shift):
    from jax import shard_map
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    def body(x):
        return jax.lax.ppermute(x, axis, perm)
    return jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                             out_specs=spec))


def _axis_size_static(axis, eager_mesh=None, traced=False):
    """Static size of the ppermute axis: under a trace the axis environment
    is authoritative (jax.lax.axis_size); eagerly, the array's mesh."""
    if traced:
        try:
            return int(jax.lax.axis_size(axis))
        except Exception:
            return None
    if eager_mesh is not None and axis in eager_mesh.axis_names:
        return eager_mesh.shape[axis]
    return None


def _p2p(tensor, peer, group, kind):
    v = tensor._value
    axis = _axis(group)
    shift, n = _p2p_shift(group, peer, kind)
    if n == 1:
        return tensor
    mesh = None if _is_traced(v) else _mesh_of(v)
    axis_n = _axis_size_static(axis, mesh, traced=_is_traced(v))
    if axis_n is not None and axis_n != n:
        # perms address AXIS indices — a group that doesn't span the mesh
        # axis would silently deliver to the wrong member
        raise ValueError(
            f"p2p group has {n} ranks but mesh axis {axis!r} has size "
            f"{axis_n}; p2p groups must span their mesh axis (use "
            "lax.ppermute directly for sub-axis patterns)")
    if _is_traced(v):
        perm = [(i, (i + shift) % n) for i in range(n)]
        out = jax.lax.ppermute(v, axis, perm)
        if kind == "recv":
            tensor._set_value(out)
            return tensor
        # keep the collective alive on sending ranks (multi-controller
        # programs must stay identical across the send/recv branches)
        return Tensor(out)
    if mesh is not None and axis in mesh.axis_names and mesh.shape[axis] > 1:
        out = _eager_ppermute_fn(mesh, v.sharding.spec, axis, shift)(v)
        if kind == "recv":
            tensor._set_value(out)
            return tensor
        return Tensor(out)
    raise RuntimeError(
        f"eager {kind} with {n} participants needs a mesh-sharded array "
        f"(global array over a Mesh with axis {axis!r}) or a traced "
        "context; a silent no-op would deliver nothing. Wrap the exchange "
        "in shard_map/jit or build the tensor over the process mesh.")


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P send to `dst` (reference communication/send.py). Lowers to a
    cyclic ppermute with shift (dst - rank) mod n — see _p2p_shift for the
    exact SPMD contract; the matching recv on rank dst produces the same
    program. Returns the permuted tensor on sending ranks so the collective
    stays live in traced graphs."""
    return _p2p(tensor, dst, group, "send")


def recv(tensor, src=0, group=None, sync_op=True):
    """P2P receive from `src` into `tensor` (in place; reference
    communication/recv.py)."""
    return _p2p(tensor, src, group, "recv")


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


class _P2PTask:
    """Completed-on-issue task handle (XLA collectives are scheduled by the
    compiler; there is no NCCL-like async stream to wait on)."""

    def __init__(self, result=None):
        self.result = result

    def wait(self):
        return self.result

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    return _P2PTask(send(tensor, dst, group))


def irecv(tensor, src=0, group=None):
    return _P2PTask(recv(tensor, src, group))


def batch_isend_irecv(p2p_op_list):
    return [_P2PTask(op.op(op.tensor, op.peer, op.group))
            for op in p2p_op_list]


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    if gather_list is not None:
        gather_list.append(Tensor(tensor._value))
    return tensor
