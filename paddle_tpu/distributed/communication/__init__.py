from .group import (Group, new_group, get_group, is_initialized,  # noqa: F401
                    destroy_process_group, wait, barrier, get_backend)
from .collectives import (all_reduce, all_gather, all_gather_object, reduce,  # noqa: F401
                          broadcast, scatter, reduce_scatter, all_to_all,
                          all_to_all_single, send, recv, isend, irecv,
                          batch_isend_irecv, P2POp, gather, ReduceOp)
from . import stream  # noqa: F401
