"""Collective watchdog + comm sanitizers (reference: paddle/phi/core/
distributed/comm_task_manager.h:37 CommTaskManager, IsTimeout :57, and the
comm NaN check in distributed/check/).

TPU-native: XLA collectives cannot hang mid-kernel the way a NCCL ring can,
but a *peer failure* (dead host in the multi-controller gang, stuck DCN
link) surfaces as an eager collective's result never becoming ready. The
watchdog waits for readiness on a worker thread with a deadline and raises
`CommTimeoutError` instead of blocking forever — the heartbeat-on-
coordination-service analog. `check_comm_result` is the comm NaN/Inf
sanitizer, gated by FLAGS_check_comm_nan.
"""
from __future__ import annotations

import threading

import jax
import numpy as np

from ... import flags
from ...resilience.faults import fault_point

__all__ = ["CommTimeoutError", "CommAggregateError", "CommTaskManager",
           "wait_with_timeout", "check_comm_result", "get_comm_task_manager"]


class CommTimeoutError(RuntimeError):
    pass


class CommAggregateError(RuntimeError):
    """Multiple tracked collectives failed in one wait_all(); `.errors` holds
    every (op_name, exception) pair so one slow collective cannot mask NaNs
    (or further timeouts) in the rest."""

    def __init__(self, message, errors):
        super().__init__(message)
        self.errors = errors


def wait_with_timeout(value, timeout: float, op_name: str = "collective"):
    """Block until `value` is ready, at most `timeout` seconds.  The
    `comm.ready` fault point simulates a peer whose collective never becomes
    ready (deterministic CPU stand-in for a dead host / stuck DCN link)."""
    if fault_point("comm.ready", op=op_name) is not None:
        raise CommTimeoutError(
            f"{op_name} not ready (injected delayed readiness) — peer "
            f"failure or hung link (reference comm_task_manager.h IsTimeout)")
    done = threading.Event()
    err = []

    def waiter():
        try:
            jax.block_until_ready(value)
        except Exception as e:  # noqa: BLE001 — surfaced to the caller below
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    if not done.wait(timeout):
        raise CommTimeoutError(
            f"{op_name} not ready after {timeout:.1f}s — peer failure or "
            f"hung link (reference comm_task_manager.h IsTimeout)")
    if err:
        raise err[0]
    return value


def check_comm_result(value, op_name: str = "collective"):
    """NaN/Inf scan on a finished collective result (reference
    distributed/check/). Active when FLAGS_check_comm_nan is set."""
    if not flags.get_flag("check_comm_nan"):
        return value
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.inexact) and not np.all(np.isfinite(arr)):
        raise FloatingPointError(f"NaN/Inf in result of {op_name}")
    return value


class CommTaskManager:
    """Tracks in-flight eager collectives (reference comm_task_manager.h:37).

    `track(op_name, value)` registers a result; `wait_all(timeout)` asserts
    every tracked result lands within the deadline, clearing the set."""

    def __init__(self, default_timeout: float = None):
        self.default_timeout = default_timeout or float(
            flags.get_flag("comm_timeout_seconds") or 1800.0)
        self._lock = threading.Lock()
        self._tasks = []

    def track(self, op_name, value):
        with self._lock:
            self._tasks.append((op_name, value))
        return value

    def pending(self):
        with self._lock:
            return len(self._tasks)

    def wait_all(self, timeout: float = None):
        """Assert every tracked result lands AND is sane within the deadline.

        Every task is checked even after one fails — a timeout mid-list must
        not leave the tail unverified (a slow collective masking a NaN in a
        later one).  The deadline is SHARED across the set (after it expires
        each remaining task gets only a short grace to prove it already
        landed), so one dead peer costs ~timeout, not N x timeout.  A single
        failure re-raises as-is; multiple failures aggregate into
        CommAggregateError naming every failed op."""
        import time as _time
        timeout = timeout or self.default_timeout
        with self._lock:
            tasks, self._tasks = self._tasks, []
        errors = []
        start = _time.monotonic()
        for name, v in tasks:
            remaining = max(0.05, timeout - (_time.monotonic() - start))
            try:
                wait_with_timeout(v, remaining, name)
                check_comm_result(v, name)
            except Exception as e:  # noqa: BLE001 — aggregated below
                errors.append((name, e))
        if len(errors) == 1:
            raise errors[0][1]
        if errors:
            detail = "; ".join(f"{n}: {type(e).__name__}: {e}"
                               for n, e in errors)
            raise CommAggregateError(
                f"{len(errors)} of {len(tasks)} tracked collectives failed "
                f"— {detail}", errors)


_manager = [None]


def get_comm_task_manager() -> CommTaskManager:
    if _manager[0] is None:
        _manager[0] = CommTaskManager()
    return _manager[0]
