"""communication.stream parity — the reference exposes stream variants of each
collective (communication/stream/); XLA has no user streams, so these alias
the sync collectives."""
from .collectives import (all_reduce, all_gather, reduce, broadcast, scatter,  # noqa: F401
                          reduce_scatter, all_to_all, all_to_all_single, send, recv)
