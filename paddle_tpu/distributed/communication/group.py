"""Process groups (reference: python/paddle/distributed/communication/group.py:29).

TPU-native: a Group names a mesh axis (or an explicit rank list) of the global
mesh; collectives over the group compile to XLA collectives over that axis.
"""
from __future__ import annotations

from typing import List, Optional

__all__ = ["Group", "new_group", "get_group", "is_initialized",
           "destroy_process_group", "wait", "barrier", "get_backend"]

_groups = {}
_next_gid = [1]


class Group:
    def __init__(self, ranks: List[int], gid: int = 0, axis_name: Optional[str] = None):
        self.ranks = list(ranks)
        self.id = gid
        self.axis_name = axis_name

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return self.nranks

    @property
    def rank(self):
        from ..env import global_rank
        r = global_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def is_member(self):
        from ..env import global_rank
        return global_rank() in self.ranks

    @property
    def process_group(self):
        return self

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks}, axis={self.axis_name})"


def _world_group():
    from ..env import get_world_size
    import jax
    n = max(get_world_size(), 1)
    if 0 not in _groups:
        _groups[0] = Group(list(range(jax.device_count())), 0, axis_name=None)
    return _groups[0]


def new_group(ranks=None, backend=None, timeout=None):
    import jax
    if ranks is None:
        ranks = list(range(jax.device_count()))
    gid = _next_gid[0]
    _next_gid[0] += 1
    g = Group(list(ranks), gid)
    _groups[gid] = g
    return g


def get_group(gid=0):
    if gid == 0:
        return _world_group()
    return _groups.get(gid)


def is_initialized():
    from ..env import is_initialized as f
    return f()


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
    else:
        _groups.pop(group.id, None)


def wait(tensor, group=None, use_calc_stream=True):
    import jax
    if hasattr(tensor, "_value"):
        jax.block_until_ready(tensor._value)


def barrier(group=None):
    import jax
    import jax.numpy as jnp
    jax.block_until_ready(jnp.zeros(()))


def get_backend(group=None):
    return "xla"
