"""Misc distributed utils."""
from __future__ import annotations

import jax

__all__ = ["get_local_rank", "get_node_count"]


def get_local_rank():
    import os
    return int(os.environ.get("PADDLE_LOCAL_RANK", 0))


def get_node_count():
    return max(jax.process_count(), 1)
