"""DataParallel + helpers (reference: python/paddle/distributed/parallel.py:219
DataParallel backed by C++ EagerReducer gradient bucketing reducer.h:88).

TPU-native: in the compiled train step, DP gradient sync is a by-product of
sharding the batch over the 'dp' mesh axis (XLA inserts the reduce-scatter/
all-reduce and overlaps it with backward — the EagerReducer's bucketing+overlap
role). Eager mode attaches grad hooks that all-reduce over 'dp' when grads
materialize, preserving the reference's semantics.
"""
from __future__ import annotations

import jax

from ..nn.layer import Layer
from ..core.tensor import Tensor
from .env import init_parallel_env, get_rank, get_world_size  # noqa: F401
from .communication.collectives import all_reduce, ReduceOp
from .communication.group import Group

__all__ = ["DataParallel", "init_parallel_env", "get_rank", "get_world_size",
           "ParallelEnv"]


class ParallelEnv:
    """reference parallel.py ParallelEnv env-var view."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        import os
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        r = get_rank()
        return eps[r] if r < len(eps) else ""

    @property
    def trainer_endpoints(self):
        import os
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training (reference parallel.py:219).

    comm_buffer_size / last_comm_buffer_size accepted for API parity; XLA's
    scheduler performs the fusion the reference's EagerReducer buckets do.
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, process_group=None):
        super().__init__()
        self._layers = layers
        self._group = group or process_group
        self.find_unused_parameters = find_unused_parameters
        self._grad_hooks = []
        if get_world_size(self._group) > 1:
            self._register_grad_hooks()

    def _register_grad_hooks(self):
        n = get_world_size(self._group)
        for p in self._layers.parameters():
            if p.stop_gradient:
                continue
            def hook(g, _n=n):
                t = g if isinstance(g, Tensor) else Tensor(g)
                all_reduce(t, op=ReduceOp.SUM, group=self._group)
                return t * (1.0 / _n)
            self._grad_hooks.append(p.register_hook(hook))

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # passthrough for state access
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    @property
    def _inner_layer(self):
        return self._layers


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """paddle.distributed.spawn parity (reference spawn.py): fork one process
    per rank with PADDLE_* env."""
    import multiprocessing as mp
    import os
    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    ctx = mp.get_context("spawn")
    procs = []
    base_port = int(options.get("started_port", 37000))
    endpoints = ",".join(f"127.0.0.1:{base_port + i}" for i in range(nprocs))
    for rank in range(nprocs):
        env = dict(os.environ)
        env.update({"PADDLE_TRAINER_ID": str(rank),
                    "PADDLE_TRAINERS_NUM": str(nprocs),
                    "PADDLE_TRAINER_ENDPOINTS": endpoints,
                    "PADDLE_MASTER": endpoints.split(",")[0]})
        p = ctx.Process(target=_spawn_entry, args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs


def _spawn_entry(func, args, env):
    import os
    os.environ.update(env)
    func(*args)
