"""Quantized AllReduce for tensor-parallel serving (EQuARX, arxiv
2506.17615): each rank's partial sum is snapped onto an int8 per-chunk
absmax grid BEFORE the reduction, so the bytes a real TP ring moves per
hop are ~4x smaller than f32 — the collective is the only cross-chip
traffic a one-AllReduce-per-layer decode pays, which makes it the whole
TP tax (PERF.md §25).

Emulation semantics (exact on any backend, including the forced-host
CPU mesh): ``quantized_allreduce(x, axis)`` fake-quantizes the LOCAL
partial — per-chunk absmax scale riding ``serving/quant.py``'s symmetric
codec (``quantize_kv``/``dequantize_kv``, the PR 15 page codec) — then
issues ONE ``jax.lax.psum`` of the dequantized partials.  That computes
bit-for-bit what an EQuARX ring computes when every hop carries int8
payloads + f32 scales and accumulates in f32: the quantization error
enters per RANK (bounded below), the reduction itself is exact.  The
``jax.lax`` attribute lookup happens at call time, so the SPMD
collective-schedule sanitizer (analysis/spmd_sanitize.py) sees the psum
like any hand-written one — a quantized AllReduce is still exactly one
schedule event per call.

Error bound: symmetric absmax rounding gives per-element error
``<= scale/2 = chunk_absmax / (2*qmax)`` per rank, so the reduced value
is within ``n_ranks * max_r(chunk_absmax_r) / (2*qmax)`` of the f32
psum — asserted by the parity test (tests/test_tp_serving.py) against
``quantized_allreduce_ref``, the single-device jnp reference that pairs
with the collective the way every Pallas kernel pairs with its ``*_ref``
(the PAR001 convention).

``allreduce(x, axis, quantized=False)`` is the f32 escape hatch the
serving engine's ``quantized_allreduce=False`` knob routes through: a
plain ``psum``, zero quantization, bit-exact reassociation-for-
reassociation with the quantized path's reduction order.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..serving.quant import dequantize_kv, quantize_kv

__all__ = ["allreduce", "fake_quant_chunks", "quantized_allreduce",
           "quantized_allreduce_ref"]

# int8 symmetric grid (the serving/quant.py KV_DTYPES int8 row); 256
# elements per absmax chunk keeps the scale overhead at f32/256 per
# element (~1.6% of the int8 payload) while tracking local dynamic range
DEFAULT_CHUNK = 256
DEFAULT_QMAX = 127.0


def fake_quant_chunks(x, *, chunk: int = DEFAULT_CHUNK,
                      qmax: float = DEFAULT_QMAX, dtype=jnp.int8):
    """Round ``x`` onto the per-chunk absmax int grid and back: the value
    an EQuARX hop would reconstruct from the wire payload.  The flattened
    tensor is split into ``chunk``-wide rows (zero-padded tail — zeros
    round-trip exactly through the symmetric codec), each row quantized
    with its own absmax scale via the PR 15 page codec, dequantized, and
    reshaped back.  Output dtype follows the input."""
    shape, d = x.shape, x.dtype
    flat = x.reshape(-1)
    n = flat.shape[0]
    c = max(1, int(chunk))
    pad = (-n) % c
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    rows = flat.reshape(-1, c)
    q, s = quantize_kv(rows, qmax=qmax, dtype=dtype)
    deq = dequantize_kv(q, s).reshape(-1)[:n]
    return deq.reshape(shape).astype(d)


def quantized_allreduce(x, axis_name, *, chunk: int = DEFAULT_CHUNK,
                        qmax: float = DEFAULT_QMAX, dtype=jnp.int8):
    """EQuARX-style AllReduce over mesh axis ``axis_name``: quantize the
    local partial per-chunk, sum the dequantized partials with ONE psum.
    Call only inside a shard_map/pmap region binding ``axis_name``."""
    return jax.lax.psum(fake_quant_chunks(x, chunk=chunk, qmax=qmax,
                                          dtype=dtype), axis_name)


def allreduce(x, axis_name, *, quantized: bool = False,
              chunk: int = DEFAULT_CHUNK, qmax: float = DEFAULT_QMAX,
              dtype=jnp.int8):
    """The serving engine's one per-layer AllReduce: f32 ``psum`` by
    default (bit-exact partial reduction), the EQuARX int8 grid with
    ``quantized=True``.  Either way it is exactly ONE collective event in
    the SPMD sanitizer's schedule."""
    if quantized:
        return quantized_allreduce(x, axis_name, chunk=chunk, qmax=qmax,
                                   dtype=dtype)
    return jax.lax.psum(x, axis_name)


def quantized_allreduce_ref(partials, *, chunk: int = DEFAULT_CHUNK,
                            qmax: float = DEFAULT_QMAX, dtype=jnp.int8):
    """Single-device jnp reference: ``partials [R, ...]`` stacked per-rank
    partial sums -> the value every rank holds after
    :func:`quantized_allreduce` (sum of per-rank fake-quantized
    partials).  The parity pair for the collective — the f32 comparison
    point is ``partials.sum(0)`` and the error bound is
    ``R * max_chunk_absmax / (2*qmax)`` per element."""
    partials = jnp.asarray(partials)
    deq = jax.vmap(lambda p: fake_quant_chunks(p, chunk=chunk, qmax=qmax,
                                               dtype=dtype))(partials)
    return deq.sum(axis=0)
