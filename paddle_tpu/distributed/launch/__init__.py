"""Launch CLI package (reference: python/paddle/distributed/launch/)."""
from .main import main, launch_gang

__all__ = ["main", "launch_gang"]
