"""Distributed launch CLI (reference: python/paddle/distributed/launch/main.py:23,
controllers/collective.py:22 CollectiveController, controllers/watcher.py).

    python -m paddle_tpu.distributed.launch --nproc_per_node=8 train.py [args...]

Spawns one process per rank with the PADDLE_* env contract the reference's
controller exports (collective.py:76,139):

    PADDLE_MASTER            coordinator host:port (jax.distributed rendezvous
                             — the TCPStore analog, store/tcp_store.h:121)
    PADDLE_TRAINER_ID        global rank
    PADDLE_TRAINERS_NUM      world size
    PADDLE_TRAINER_ENDPOINTS comma list of all rank endpoints
    PADDLE_LOCAL_RANK        rank on this node
    PADDLE_NNODES / PADDLE_NODE_RANK

`paddle_tpu.distributed.env.init_parallel_env` consumes these and calls
`jax.distributed.initialize`. On TPU pods each process drives its local
chips; on CPU (tests) each process is pinned to one virtual device.

The watcher polls children: if any rank exits non-zero the rest are
terminated (reference controller.py:35 watch loop). `--max_restarts N`
relaunches the whole gang on failure (the elastic-controller restart
semantic, collective.py:267 — peer discovery via etcd is out of scope;
membership is the static endpoint list).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["main", "launch_gang"]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher",
        allow_abbrev=False)  # '--np' must never be read as --nproc_per_node
    p.add_argument("--nproc_per_node", type=int, default=None)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", type=str, default=None,
                   help="host:port of the coordination service (default: "
                        "a free local port; required multi-node)")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="relaunch the gang up to N times on failure")
    p.add_argument("--np", type=str, default=None,
                   help="elastic range 'min:max' (reference --np): start at "
                        "max procs, scale in toward min on repeated failure")
    p.add_argument("--elastic_store", type=str, default=None,
                   help="directory for a FileStore membership store: external "
                        "workers joining it trigger a live scale-OUT (gang "
                        "interrupt + relaunch at the larger world, ranks "
                        "resuming from their checkpoint — reference "
                        "fleet/elastic/manager.py watch->re-rank->restart)")
    p.add_argument("--devices", type=str, default=None,
                   help="comma list of device ids to pin per local rank")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _rank_env(base_env, *, rank, local_rank, world, master, endpoints,
              nnodes, node_rank, devices=None):
    env = dict(base_env)
    env.update({
        "PADDLE_MASTER": master,
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_TRAINER_ENDPOINTS": endpoints,
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_NNODES": str(nnodes),
        "PADDLE_NODE_RANK": str(node_rank),
        "PADDLE_RANK_IN_NODE": str(local_rank),
        # torch-style aliases many scripts read
        "RANK": str(rank),
        "WORLD_SIZE": str(world),
        "LOCAL_RANK": str(local_rank),
        "MASTER_ADDR": master.split(":")[0],
        "MASTER_PORT": master.split(":")[1],
    })
    if devices is not None:
        dev = devices[local_rank % len(devices)]
        env["PADDLE_SELECTED_DEVICES"] = dev
        # actually pin the rank to its accelerator (reference launch exports
        # CUDA_VISIBLE_DEVICES; TPU runtimes read TPU_VISIBLE_CHIPS)
        env["CUDA_VISIBLE_DEVICES"] = dev
        env["TPU_VISIBLE_CHIPS"] = dev
    return env


#: sentinel return: the gang was interrupted by a membership change (the
#: elastic loop relaunches at the new world size)
MEMBERSHIP_CHANGED = -257


def launch_gang(cmd, *, nproc, master=None, nnodes=1, node_rank=0,
                env=None, log_dir=None, max_restarts=0, devices=None,
                poll_interval=0.5, interrupt_check=None):
    """Spawn and watch a gang of `nproc` rank processes running `cmd`
    (a list, the per-rank argv). Returns the max child return code.

    interrupt_check: optional callable polled with the children; returning
    True terminates the gang and returns MEMBERSHIP_CHANGED (elastic
    scale-out: a joiner arrived and the gang must re-rank)."""
    base_env = dict(os.environ if env is None else env)
    if master is None:
        master = f"127.0.0.1:{_free_port()}"
    world = nproc * nnodes
    rank0 = node_rank * nproc
    host = master.split(":")[0]
    endpoints = ",".join(
        f"{host}:{_free_port()}" for _ in range(world))
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)

    attempts = 0
    while True:
        procs = []
        logs = []
        for lr in range(nproc):
            rank = rank0 + lr
            renv = _rank_env(base_env, rank=rank, local_rank=lr, world=world,
                             master=master, endpoints=endpoints,
                             nnodes=nnodes, node_rank=node_rank,
                             devices=devices)
            if log_dir:
                lf = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
                logs.append(lf)
                out = lf
            else:
                out = None  # inherit
            procs.append(subprocess.Popen(cmd, env=renv, stdout=out,
                                          stderr=subprocess.STDOUT if out else None))

        def _terminate_all(sig=signal.SIGTERM):
            for pr in procs:
                if pr.poll() is None:
                    try:
                        pr.send_signal(sig)
                    except OSError:
                        pass

        def _stop_gang():
            _terminate_all()
            deadline = time.time() + 10
            for pr in procs:
                t = max(0.1, deadline - time.time())
                try:
                    pr.wait(timeout=t)
                except subprocess.TimeoutExpired:
                    pr.kill()

        prev_handlers = {}
        for s in (signal.SIGINT, signal.SIGTERM):
            try:
                prev_handlers[s] = signal.signal(
                    s, lambda *_: (_terminate_all(), sys.exit(1)))
            except ValueError:
                pass  # not main thread

        rc = 0
        try:
            # watcher loop (reference controller.py:35): any failure kills the gang
            while True:
                codes = [pr.poll() for pr in procs]
                failed = [c for c in codes if c not in (None, 0)]
                if failed:
                    _stop_gang()
                    rc = max(failed)
                    break
                if all(c == 0 for c in codes):
                    rc = 0
                    break
                if interrupt_check is not None and interrupt_check():
                    _stop_gang()
                    rc = MEMBERSHIP_CHANGED
                    break
                time.sleep(poll_interval)
        finally:
            for s, h in prev_handlers.items():
                signal.signal(s, h)
            for lf in logs:
                lf.close()

        if rc == 0 or rc == MEMBERSHIP_CHANGED or attempts >= max_restarts:
            return rc
        attempts += 1
        # elastic-style gang restart on a fresh rendezvous port
        master = f"127.0.0.1:{_free_port()}"
        print(f"[launch] gang failed rc={rc}; restart {attempts}/{max_restarts}",
              file=sys.stderr)


def main(argv=None):
    args = _parse_args(sys.argv[1:] if argv is None else argv)
    # drop only a single leading "--" separator; later "--" belong to the script
    script_args = list(args.script_args)
    if script_args and script_args[0] == "--":
        script_args = script_args[1:]
    cmd = [sys.executable, "-u", args.script] + script_args
    devices = args.devices.split(",") if args.devices else None
    if args.np:
        if args.nproc_per_node is not None:
            sys.exit("[launch] --np and --nproc_per_node are mutually "
                     "exclusive")
        if args.nnodes != 1:
            sys.exit("[launch] elastic --np supports single-node gangs only "
                     "(multi-node membership needs a shared coordination "
                     "store; see fleet.elastic.ElasticManager + FileStore)")
        parts = args.np.split(":")
        try:
            np_min = int(parts[0])
            np_max = int(parts[1]) if len(parts) > 1 else np_min
            if len(parts) > 2 or np_min < 1 or np_min > np_max:
                raise ValueError
        except ValueError:
            sys.exit(f"[launch] invalid --np {args.np!r}: expected "
                     "'min:max' with 1 <= min <= max")
        sys.exit(_elastic_loop(cmd, np_min, np_max, args, devices,
                                store_dir=args.elastic_store))
    nproc = args.nproc_per_node if args.nproc_per_node is not None else \
        int(os.environ.get("PADDLE_NPROC_PER_NODE", 1))
    rc = launch_gang(cmd, nproc=nproc, master=args.master,
                     nnodes=args.nnodes, node_rank=args.node_rank,
                     log_dir=args.log_dir, max_restarts=args.max_restarts,
                     devices=devices)
    sys.exit(rc)


def _elastic_loop(cmd, np_min, np_max, args, devices, store_dir=None):
    """Elastic mode (reference CollectiveElasticController): the membership
    store holds one slot per local worker; a gang failure retires a slot
    (the node-leave analog) and the gang relaunches at the surviving
    member count, giving up once membership drops below np_min.

    With --elastic_store the membership lives in a FileStore that EXTERNAL
    joiners can register into: the watch loop interrupts a running gang on
    a membership change and relaunches at the new (larger) world with a
    regenerated rank map — the scale-OUT path (reference
    fleet/elastic/manager.py watch -> re-rank -> restart on join)."""
    from ..fleet.elastic import ElasticManager, MemoryStore, FileStore

    if store_dir:
        # finite lease: a crashed joiner (or a previous run's members) age
        # out instead of inflating the gang forever; the watch poll below
        # re-heartbeats this launcher's own slots
        store = FileStore(store_dir)
        mgr = ElasticManager(store, np_min=np_min, np_max=np_max,
                             heartbeat_timeout=60.0, grace_period=0.0)
        own = [f"local:{i}" for i in range(np_min)]
        for h in own:                # joiners grow the gang toward np_max
            mgr.register(h)
    else:
        store = MemoryStore()
        mgr = ElasticManager(store, np_min=np_min, np_max=np_max,
                             heartbeat_timeout=1e9, grace_period=0.0)
        for i in range(np_max):
            mgr.register(f"local:{i}")
    mgr.watch()                                  # seed the stable membership

    def membership_changed():
        if not store_dir:
            return False
        from ..fleet.elastic import ElasticStatus
        for h in own:
            mgr.heartbeat(h)
        return mgr.watch() == ElasticStatus.CHANGE

    try:
        return _elastic_run(cmd, np_min, mgr, args, devices,
                            membership_changed)
    finally:
        if store_dir:
            for h in own:
                mgr.deregister(h)    # don't resurrect in a reused store dir


def _elastic_run(cmd, np_min, mgr, args, devices, membership_changed):
    while True:
        world = len(mgr.members())
        rc = launch_gang(cmd, nproc=world, master=args.master,
                         nnodes=1, node_rank=0, log_dir=args.log_dir,
                         max_restarts=args.max_restarts, devices=devices,
                         interrupt_check=membership_changed)
        if rc == MEMBERSHIP_CHANGED:
            new_world = len(mgr.members())
            print(f"[launch] elastic: membership changed {world} -> "
                  f"{new_world}; re-ranking and restarting "
                  f"(rank map: {mgr.rank_map()})", file=sys.stderr)
            continue
        if rc == 0:
            return 0
        # retire one slot and consult the manager
        mgr.deregister(mgr.members()[-1])
        mgr.watch()
        if len(mgr.members()) < np_min:
            print(f"[launch] elastic: below np_min={np_min}; giving up",
                  file=sys.stderr)
            return rc
        print(f"[launch] elastic: gang of {world} failed rc={rc}; "
              f"scaling in to {len(mgr.members())}", file=sys.stderr)


if __name__ == "__main__":
    main()
