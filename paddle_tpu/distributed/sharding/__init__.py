"""Group sharded (ZeRO) API (reference: python/paddle/distributed/sharding/
group_sharded.py group_sharded_parallel + fleet meta_parallel/sharding/ —
stage2 optimizer/model, stage3 group_sharded_stage3.py; mechanics in
SURVEY.md §2.1 "ZeRO-3 mechanics").

TPU-native: ZeRO states are sharding specs, not runtime machinery —
* stage 1: optimizer state arrays placed with NamedSharding over 'sharding'
* stage 2: + gradients reduce-scattered (XLA emits reduce-scatter when the
  grad spec is sharded in the compiled step)
* stage 3: + parameters sharded, re-gathered per-layer inside the step
  (explicit all_gather in the sharded step fn + XLA buffer donation frees the
  gathered copy — the forward-prehook gather / posthook release analog).

The eager wrapper shards the optimizer accumulators; the compiled path in
paddle_tpu.parallel.sharded applies the specs to the whole train step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ..topology import get_default_mesh

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "shard_accumulator_specs"]


def _shard_axis_for(value, mesh, axis="sharding"):
    """Choose the largest tensor dim divisible by the axis size (the flat
    per-rank slice buffer analog of _param2buffer, group_sharded_stage3.py:174)."""
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return None
    n = mesh.shape[axis]
    for d, s in enumerate(value.shape):
        if s % n == 0 and s >= n:
            return d
    return None


def shard_accumulator_specs(params, mesh=None, axis="sharding"):
    """{name: PartitionSpec} for optimizer accumulators (stage-1 layout)."""
    mesh = mesh or get_default_mesh()
    specs = {}
    for name, v in params.items():
        d = _shard_axis_for(v, mesh, axis)
        entries = [None] * v.ndim
        if d is not None:
            entries[d] = axis
        specs[name] = P(*entries)
    return specs


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """reference group_sharded.py group_sharded_parallel(level='os'|'os_g'|'p_g_os')."""
    mesh = get_default_mesh()
    axis = "sharding" if "sharding" in mesh.axis_names and mesh.shape.get("sharding", 1) > 1 \
        else ("dp" if "dp" in mesh.axis_names else None)
    if axis is None or mesh.shape[axis] <= 1:
        return model, optimizer, scaler

    # stage >= 1: shard existing/future optimizer accumulators
    orig_init_state = optimizer._init_state

    def sharded_init_state(value):
        state = orig_init_state(value)
        d = _shard_axis_for(value, mesh, axis)
        if d is None:
            return state
        entries = [None] * value.ndim
        entries[d] = axis
        sh = NamedSharding(mesh, P(*entries))
        out = {}
        for k, v in state.items():
            if hasattr(v, "shape") and v.shape == value.shape:
                out[k] = jax.device_put(v, sh)
            else:
                out[k] = v
        return out
    optimizer._init_state = sharded_init_state

    if level in ("p_g_os", "p_g_os3", 3, "stage3"):
        # stage 3: shard parameters themselves
        for p in model.parameters():
            d = _shard_axis_for(p._value, mesh, axis)
            if d is None:
                continue
            entries = [None] * p._value.ndim
            entries[d] = axis
            p._set_value(jax.device_put(p._value, NamedSharding(mesh, P(*entries))))
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from ... import framework
    os.makedirs(output, exist_ok=True)
    framework.save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        framework.save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
