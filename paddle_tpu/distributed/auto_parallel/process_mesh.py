"""ProcessMesh (reference: python/paddle/distributed/auto_parallel/
process_mesh.py:85; C++ paddle/phi/core/distributed/auto_parallel/process_mesh.h).

TPU-native: a named view over jax devices that lowers to jax.sharding.Mesh.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["ProcessMesh", "get_mesh", "set_mesh"]

_GLOBAL_MESH: Optional["ProcessMesh"] = None


class ProcessMesh:
    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None,
                 shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids).reshape(shape)
        self._ids = arr
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._ids

    def get_dim_size(self, name):
        return self._ids.shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, process_id):
        axis = self._dim_names.index(dim) if isinstance(dim, str) else dim
        coords = np.argwhere(self._ids == process_id)
        return int(coords[0][axis]) if len(coords) else -1

    def to_jax(self) -> Mesh:
        """Lower to jax.sharding.Mesh over the matching device objects."""
        if self._jax_mesh is None:
            devices = jax.devices()
            grid = np.empty(self._ids.shape, dtype=object)
            for idx in np.ndindex(self._ids.shape):
                grid[idx] = devices[int(self._ids[idx]) % len(devices)]
            self._jax_mesh = Mesh(grid, axis_names=tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    def __enter__(self):
        global _GLOBAL_MESH
        self._prev = _GLOBAL_MESH
        _GLOBAL_MESH = self
        return self

    def __exit__(self, *exc):
        global _GLOBAL_MESH
        _GLOBAL_MESH = self._prev
        return False


def get_mesh() -> Optional[ProcessMesh]:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        n = jax.device_count()
        _GLOBAL_MESH = ProcessMesh(np.arange(n), dim_names=["dp"])
    return _GLOBAL_MESH


def set_mesh(mesh: ProcessMesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    return mesh
