"""Placements (reference: python/paddle/distributed/auto_parallel/
placement_type.py; C++ placement_types.h): Shard(dim) / Replicate / Partial.
They translate to jax PartitionSpec entries.
"""
from __future__ import annotations

__all__ = ["Placement", "Shard", "Replicate", "Partial", "to_partition_spec"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return True if dim is None else dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    """Pending-reduction placement. XLA tracks partial sums implicitly inside
    compiled fns; an eager DTensor marked Partial is reduced on first use
    (reference reshard p_to_r)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial({self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))


def to_partition_spec(placements, mesh, ndim):
    """[Placement,...] (one per mesh dim) -> PartitionSpec over tensor dims."""
    from jax.sharding import PartitionSpec
    entries = [None] * ndim
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            axis_name = mesh.dim_names[mesh_dim]
            cur = entries[p.dim]
            if cur is None:
                entries[p.dim] = axis_name
            elif isinstance(cur, tuple):
                entries[p.dim] = cur + (axis_name,)
            else:
                entries[p.dim] = (cur, axis_name)
    return PartitionSpec(*entries)
