from .process_mesh import ProcessMesh, get_mesh, set_mesh  # noqa: F401
from .placement_type import Shard, Replicate, Partial, Placement  # noqa: F401
from .api import (shard_tensor, reshard, shard_layer, shard_optimizer,  # noqa: F401
                  dtensor_from_fn, unshard_dtensor, is_dist_tensor,
                  shard_dataloader, Strategy, to_static)
from .static_engine import DistModel, Engine
