"""Static auto-parallel Engine + DistModel (reference:
python/paddle/distributed/auto_parallel/static/engine.py:99 Engine,
auto_parallel/api.py:2254 DistModel / :2952 to_static).

TPU-native collapse: the reference's static pipeline (completion →
partition → reshard passes over a static Program, then executor runs) is
GSPMD's job. `DistModel` captures the Layer + loss + optimizer as ONE jitted
SPMD train step: parameters keep whatever NamedSharding `shard_tensor` /
`shard_layer` gave them (replicated otherwise), jit's in_shardings pick them
up, XLA propagates and inserts the collectives, and buffer donation updates
in place. `Engine` is the fit/evaluate/predict driver over it.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...nn.layer import Layer, functional_state

__all__ = ["DistModel", "Engine", "to_static"]


def _coerce(v):
    if isinstance(v, Tensor):
        return v._value
    return jnp.asarray(v)


class DistModel:
    """Compiled SPMD train/eval wrapper (reference DistModel api.py:2254).

    Modes mirror the reference: `train()` → __call__(x, y) runs a train
    step and returns the loss; `eval()` → returns the loss without update;
    `predict()` → returns outputs.
    """

    def __init__(self, layer: Layer, loss=None, optimizer=None,
                 strategy=None):
        if optimizer is not None and loss is None:
            raise ValueError(
                "DistModel: an optimizer was given without a loss — "
                "training needs loss(outputs, labels)")
        self.network = layer
        self._loss = loss
        self._opt = optimizer
        self._mode = "train" if optimizer is not None else "predict"
        self.params = {n: p._value for n, p in layer.named_parameters()
                       if not p.stop_gradient}
        self._frozen = {n: p._value for n, p in layer.named_parameters()
                        if p.stop_gradient}
        self.opt_state = optimizer.init_opt_state(self.params) \
            if optimizer is not None else None
        self._train_step = None
        self._eval_step = None
        self._pred_step = None

    # -- modes --------------------------------------------------------------
    def train(self):
        self._mode = "train"
        self.network.train()
        return self

    def eval(self):
        self._mode = "eval"
        self.network.eval()
        return self

    def predict(self):
        self._mode = "predict"
        self.network.eval()
        return self

    def dist_main_program(self, mode=None):  # reference API parity
        return None

    # -- compiled steps -----------------------------------------------------
    def _loss_of(self, params, x, y):
        full = dict(params)
        full.update(self._frozen)
        with functional_state(self.network, full):
            out = self.network(Tensor(x))
        lt = self._loss(out, Tensor(y))
        return (lt._value if isinstance(lt, Tensor) else lt).astype(jnp.float32)

    def _build_train(self):
        opt = self._opt

        def step(params, opt_state, lr, x, y):
            loss, g = jax.value_and_grad(self._loss_of)(params, x, y)
            new_p, new_o = opt.apply_gradients_functional(
                params, g, opt_state, lr=lr)
            return new_p, new_o, loss

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_eval(self):
        return jax.jit(self._loss_of)

    def _build_pred(self):
        def fwd(params, x):
            full = dict(params)
            full.update(self._frozen)
            with functional_state(self.network, full):
                out = self.network(Tensor(x))
            return jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))
        return jax.jit(fwd)

    def __call__(self, *args):
        if self._mode == "train":
            x, y = (_coerce(a) for a in args)
            if self._train_step is None:
                self._train_step = self._build_train()
            lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
            self.params, self.opt_state, loss = self._train_step(
                self.params, self.opt_state, lr, x, y)
            self._opt.finish_step()
            return Tensor(loss)
        if self._mode == "eval":
            x, y = (_coerce(a) for a in args)
            if self._eval_step is None:
                self._eval_step = self._build_eval()
            return Tensor(self._eval_step(self.params, x, y))
        x = _coerce(args[0])
        if self._pred_step is None:
            self._pred_step = self._build_pred()
        out = self._pred_step(self.params, x)
        return jax.tree_util.tree_map(Tensor, out)

    # -- state --------------------------------------------------------------
    def state_dict(self, mode="all"):
        return dict(self.params)

    def sync_to_network(self):
        targets = dict(self.network.named_parameters())
        for n, v in self.params.items():
            if n in targets:
                targets[n]._set_value(v)
        for n, v in self._frozen.items():
            if n in targets:
                targets[n]._set_value(v)


class Engine:
    """reference static/engine.py:99 — prepare/fit/evaluate/predict driver."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics or []
        self.strategy = strategy
        self._dist = DistModel(model, loss=loss, optimizer=optimizer,
                               strategy=strategy)
        self.history = {"loss": []}

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        getattr(self._dist, mode)()
        return self

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None,
            log_freq=10, verbose=0):
        self._dist.train()
        for _ in range(epochs):
            for step_i, batch in enumerate(train_data):
                if steps_per_epoch is not None and step_i >= steps_per_epoch:
                    break
                x, y = batch
                loss = self._dist(x, y)
                self.history["loss"].append(float(loss.numpy()))
        self._dist.sync_to_network()
        return self.history

    def cost(self, inputs_spec, labels_spec=None, mode="train"):
        """Compiled-HLO cost summary for the current sharding plan
        (reference auto_parallel/static/cost/: the cost model the static
        pipeline consults; here the COMPILED program is the model — XLA has
        already placed the collectives, so counting them and reading the
        compiler's own cost/memory analysis explains WHY this plan costs
        what it does, without running a step).

        inputs_spec / labels_spec: shape/dtype specs (anything with .shape
        and .dtype, e.g. static.InputSpec or jax.ShapeDtypeStruct); every
        dim must be concrete — costs are per-shape.
        Returns {flops, bytes_accessed, peak_hbm_bytes, collectives: {...},
        output_bytes}.  The lowered step is cached on the DistModel, so a
        later fit()/evaluate() reuses the same compilation.
        """
        import re as _re

        if mode not in ("train", "eval", "predict"):
            raise ValueError(f"cost(): unknown mode {mode!r}")
        if mode in ("train", "eval") and labels_spec is None:
            raise ValueError(f"cost(mode={mode!r}) requires labels_spec")
        if mode == "train" and self.optimizer is None:
            raise ValueError("cost(mode='train') requires an optimizer")
        if mode in ("train", "eval") and self.loss is None:
            raise ValueError(f"cost(mode={mode!r}) requires a loss")

        def _sds(spec):
            if any(d is None for d in spec.shape):
                raise ValueError(
                    f"cost() needs concrete dims, got {tuple(spec.shape)} — "
                    "costs are per-shape (substitute the real batch size)")
            return jax.ShapeDtypeStruct(tuple(int(d) for d in spec.shape),
                                        jnp.dtype(spec.dtype))

        x_sd = _sds(inputs_spec)
        d = self._dist
        if mode == "train":
            if d._train_step is None:
                d._train_step = d._build_train()
            lr_sd = jax.ShapeDtypeStruct((), jnp.float32)
            lowered = d._train_step.lower(d.params, d.opt_state, lr_sd,
                                          x_sd, _sds(labels_spec))
        elif mode == "eval":
            if d._eval_step is None:
                d._eval_step = d._build_eval()
            lowered = d._eval_step.lower(d.params, x_sd, _sds(labels_spec))
        else:
            if d._pred_step is None:
                d._pred_step = d._build_pred()
            lowered = d._pred_step.lower(d.params, x_sd)
        compiled = lowered.compile()
        hlo = compiled.as_text()
        colls = {}
        for op in ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all"):
            n = len(_re.findall(rf"\b{op}(?:-start)?\.?\d*\s*=", hlo))
            if n:
                colls[op] = n
        try:
            ca = compiled.cost_analysis() or {}
        except Exception:
            ca = {}
        try:
            ma = compiled.memory_analysis()
            peak = getattr(ma, "temp_size_in_bytes", None)
        except Exception:
            peak = None
        return {
            "flops": ca.get("flops"),
            "bytes_accessed": ca.get("bytes accessed"),
            # jax's key for the output operand is 'bytes accessedout{}'
            "output_bytes": ca.get("bytes accessedout{}"),
            "peak_hbm_bytes": peak,
            "collectives": colls,
        }

    def evaluate(self, valid_data, steps=None):
        self._dist.eval()
        losses = []
        for i, (x, y) in enumerate(valid_data):
            if steps is not None and i >= steps:
                break
            losses.append(float(self._dist(x, y).numpy()))
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, test_data, steps=None):
        self._dist.predict()
        outs = []
        for i, batch in enumerate(test_data):
            if steps is not None and i >= steps:
                break
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            outs.append(self._dist(x))
        return outs

    def save(self, path, training=True):
        from ... import framework
        self._dist.sync_to_network()
        state = {n: np.asarray(v) for n, v in self._dist.params.items()}
        # frozen params/buffers (BN running stats etc.) must round-trip too
        state.update({n: np.asarray(v)
                      for n, v in self._dist._frozen.items()})
        framework.save(state, path + ".pdparams")

    def load(self, path):
        from ... import framework
        state = framework.load(path + ".pdparams", return_numpy=True)
        for n in list(self._dist.params):
            if n in state:
                self._dist.params[n] = jnp.asarray(state[n])
        for n in list(self._dist._frozen):
            if n in state:
                self._dist._frozen[n] = jnp.asarray(state[n])
        self._dist.sync_to_network()


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None):
    """reference api.py:2952 — build the compiled DistModel."""
    dm = DistModel(layer, loss=loss, optimizer=optimizer, strategy=strategy)
    if optimizer is None:
        dm.predict()
    return dm
