"""Semi-auto parallel DTensor API (reference:
python/paddle/distributed/auto_parallel/api.py — shard_tensor :220, reshard
:797, shard_layer :908, shard_optimizer :1735; C++ DistTensor dist_tensor.h +
119 SPMD rule files + reshard funcs).

TPU-native collapse (SURVEY.md §2.1): a "DistTensor" is simply a Tensor whose
jax.Array carries a NamedSharding; GSPMD does sharding propagation (replacing
the SPMD rules) and `reshard` is `jax.device_put` with a new sharding (the
r2s/s2r/p2r reshard function family collapses into one primitive).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Tensor, Parameter
from .process_mesh import ProcessMesh, get_mesh
from .placement_type import Placement, Shard, Replicate, Partial, to_partition_spec

__all__ = ["shard_tensor", "reshard", "shard_layer", "shard_optimizer",
           "dtensor_from_fn", "unshard_dtensor", "is_dist_tensor",
           "get_placements", "shard_dataloader", "ShardDataloader",
           "to_static", "Strategy"]


def _named_sharding(mesh: ProcessMesh, placements, ndim) -> NamedSharding:
    spec = to_partition_spec(placements, mesh, ndim)
    return NamedSharding(mesh.to_jax(), spec)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None):
    """reference api.py:220. Places the tensor's jax.Array with the requested
    NamedSharding (device_put distributes shards across the mesh)."""
    t = data if isinstance(data, Tensor) else Tensor(jnp.asarray(data))
    sh = _named_sharding(mesh, placements, t._value.ndim)
    v = jax.device_put(t._value, sh)
    if isinstance(t, Parameter):
        out = Parameter(v, trainable=t.trainable, name=t.name)
        out.stop_gradient = t.stop_gradient
    else:
        out = Tensor(v, stop_gradient=t.stop_gradient if stop_gradient is None
                     else stop_gradient, name=t.name)
    out._dist_mesh = mesh
    out._placements = list(placements)
    return out


def reshard(dist_tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """reference api.py:797 — all reshard transforms (s2r/r2s/p2r/x-mesh) are
    jax.device_put with the target sharding; XLA emits the collectives."""
    t = dist_tensor
    # Partial → materialize reduction first is implicit: jax arrays never hold
    # unreduced partials eagerly.
    sh = _named_sharding(mesh, placements, t._value.ndim)
    v = jax.device_put(t._value, sh)
    out = Tensor(v, stop_gradient=t.stop_gradient, name=t.name)
    out._grad_node = t._grad_node
    out._out_index = t._out_index
    out._dist_mesh = mesh
    out._placements = list(placements)
    return out


def is_dist_tensor(t) -> bool:
    try:
        return isinstance(t._value.sharding, NamedSharding)
    except Exception:
        return False


def get_placements(t):
    return getattr(t, "_placements", None)


def shard_layer(layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None):
    """reference api.py:908: apply shard_fn(name, sublayer, mesh) to every
    sublayer (default: replicate all params over the mesh)."""
    def default_shard_fn(name, sublayer, mesh):
        for pname, p in list(sublayer._parameters.items()):
            if p is None:
                continue
            rep = [Replicate() for _ in range(mesh.ndim)]
            sublayer._parameters[pname] = shard_tensor(p, mesh, rep)
    fn = shard_fn or default_shard_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None, gradient_accumulation_steps=1):
    """reference api.py:1735: shard optimizer states across the mesh. The
    functional analog: accumulator arrays inherit their parameter's sharding
    (or shard_fn's choice) when first created — on TPU this happens lazily at
    first step(); we pre-place existing states here."""
    for p in optimizer._parameter_list:
        state = optimizer._accumulators.get(id(p))
        if state is None:
            continue
        try:
            sh = p._value.sharding
        except Exception:
            continue
        for k, v in state.items():
            if hasattr(v, "shape") and v.shape == p._value.shape:
                state[k] = jax.device_put(v, sh)
    return optimizer


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def unshard_dtensor(dist_tensor):
    v = dist_tensor._value
    full = jax.device_put(v, jax.devices()[0])
    return Tensor(full, stop_gradient=dist_tensor.stop_gradient)


class ShardDataloader:
    """reference api.py:3475 shard_dataloader: wraps a DataLoader so each
    batch lands sharded over the mesh's dp-like axis."""

    def __init__(self, dataloader, meshes, input_keys=None, shard_dims=None,
                 is_dataset_splitted=False):
        self._loader = dataloader
        self._mesh = meshes if isinstance(meshes, ProcessMesh) else meshes[0]
        self._shard_dims = shard_dims

    def __len__(self):
        return len(self._loader)

    def __iter__(self):
        dim = self._shard_dims if isinstance(self._shard_dims, str) else \
            (self._mesh.dim_names[0])
        for batch in self._loader:
            yield jax.tree_util.tree_map(
                lambda t: shard_tensor(
                    t, self._mesh,
                    [Shard(0) if n == dim else Replicate()
                     for n in self._mesh.dim_names])
                if isinstance(t, Tensor) else t,
                batch, is_leaf=lambda x: isinstance(x, Tensor))


def shard_dataloader(dataloader, meshes, input_keys=None, shard_dims=None,
                     is_dataset_splitted=False):
    return ShardDataloader(dataloader, meshes, input_keys, shard_dims,
                           is_dataset_splitted)


class Strategy:
    """auto-parallel Strategy config holder (reference api.py:1973)."""

    def __init__(self, config=None):
        self.sharding = _SubCfg(enable=False, stage=1, degree=1)
        self.fused_passes = _SubCfg(enable=False, fused_passes_list=[])
        self.gradient_merge = _SubCfg(enable=False, k_steps=1, avg=True)
        self.pipeline = _SubCfg(enable=False, schedule_mode="1F1B",
                                micro_batch_size=1, accumulate_steps=1)
        self.amp = _SubCfg(enable=False, dtype="bfloat16", level="O1")
        self.recompute = _SubCfg(enable=False)
        if config:
            for k, v in config.items():
                if hasattr(self, k) and isinstance(v, dict):
                    for kk, vv in v.items():
                        setattr(getattr(self, k), kk, vv)


class _SubCfg:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None):
    """reference api.py:2952 — returns the compiled DistModel
    (static_engine.DistModel: one jitted SPMD train step with GSPMD doing
    the completion/partition/reshard passes)."""
    from .static_engine import to_static as _ts
    return _ts(layer, loader=loader, loss=loss, optimizer=optimizer,
               strategy=strategy, input_spec=input_spec)
