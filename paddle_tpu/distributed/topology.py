"""Device mesh topology (reference: python/paddle/distributed/fleet/base/
topology.py:70 CommunicateTopology / :189 HybridCommunicateGroup).

TPU-native: one global `jax.sharding.Mesh` whose named axes are the
parallelism dimensions (dp, sharding, pp, sep, mp, ep). Axis order follows the
reference's hybrid order (topology.py hybrid_group_names) so that adjacent
ranks share the fastest-varying axis (mp innermost → rides ICI nearest
neighbors, exactly the reference's NCCL ring placement logic).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

_DEFAULT_MESH: Optional[Mesh] = None
_HYBRID_ORDER = ["dp", "pp", "sharding", "sep", "mp"]


def build_mesh(axes: Dict[str, int], devices=None) -> Mesh:
    """Create a named mesh over the device grid. Axis sizes must multiply to
    the device count (singleton axes allowed)."""
    devices = list(devices if devices is not None else jax.devices())
    names = [n for n in axes]
    sizes = [int(axes[n]) for n in names]
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {axes} needs {total} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        grid = mesh_utils.create_device_mesh(sizes, devices=devices)
    except Exception:
        grid = np.asarray(devices).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def get_default_mesh() -> Mesh:
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        reset_default_mesh()
    return _DEFAULT_MESH


def set_default_mesh(mesh: Mesh):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh
    return mesh


def reset_default_mesh():
    """Default: 1-D data-parallel mesh over all devices."""
    global _DEFAULT_MESH
    _DEFAULT_MESH = build_mesh({"dp": jax.device_count()})
    return _DEFAULT_MESH


class CommunicateTopology:
    """nd rank grid helper (reference topology.py:70)."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "sep", "model"),
                 dims=(1, 1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world = int(np.prod(self._dims))
        self._grid = np.arange(self._world).reshape(self._dims)

    def get_hybrid_group_names(self):
        return self._names

    def get_dim(self, name):
        return self._dims[self._names.index(name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        idx = tuple(kwargs[n] for n in self._names)
        return int(self._grid[idx])

    def get_coord(self, rank):
        coords = np.unravel_index(rank, self._dims)
        import collections
        C = collections.namedtuple("Coord", self._names)
        return C(*[int(c) for c in coords])

    def get_axis_list(self, axis_name, index):
        axis = self._names.index(axis_name)
        sl = [slice(None)] * len(self._dims)
        sl[axis] = index
        return sorted(self._grid[tuple(sl)].reshape(-1).tolist())

    def get_comm_list(self, axis_name):
        """All groups along `axis_name`: list of rank lists."""
        axis = self._names.index(axis_name)
        moved = np.moveaxis(self._grid, axis, -1)
        return moved.reshape(-1, self._dims[axis]).tolist()


class HybridCommunicateGroup:
    """reference topology.py:189 — holds per-axis group info; on TPU the
    'groups' are mesh axes of the global mesh rather than NCCL communicators."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        from .env import global_rank
        self.global_rank = global_rank()
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep")
        self._mp_degree = topology.get_dim("model")
        axes = {}
        for ref_name, mesh_name in (("data", "dp"), ("pipe", "pp"),
                                    ("sharding", "sharding"), ("sep", "sep"),
                                    ("model", "mp")):
            axes[mesh_name] = topology.get_dim(ref_name)
        # drop singleton axes? keep all — pjit handles size-1 axes fine
        self.mesh = build_mesh(axes) if int(np.prod(list(axes.values()))) == \
            len(jax.devices()) else None
        if self.mesh is not None:
            set_default_mesh(self.mesh)

    # degree queries (reference API)
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def _coord(self):
        return self._topo.get_coord(self.global_rank)

    def get_data_parallel_rank(self):
        return self._coord().data

    def get_model_parallel_rank(self):
        return self._coord().model

    def get_stage_id(self):
        return self._coord().pipe

    def get_sharding_parallel_rank(self):
        return self._coord().sharding

    def get_sep_parallel_rank(self):
        return self._coord().sep

    def topology(self):
        return self._topo

    # group objects (mesh-axis handles)
    def get_data_parallel_group(self):
        from .communication.group import Group
        return Group(self._topo.get_axis_list("data", 0), axis_name="dp")

    def get_model_parallel_group(self):
        from .communication.group import Group
        return Group(self._topo.get_axis_list("model", 0), axis_name="mp")

    def get_pipe_parallel_group(self):
        from .communication.group import Group
        return Group(self._topo.get_axis_list("pipe", 0), axis_name="pp")

    def get_sharding_parallel_group(self):
        from .communication.group import Group
        return Group(self._topo.get_axis_list("sharding", 0), axis_name="sharding")

    def get_sep_parallel_group(self):
        from .communication.group import Group
        return Group(self._topo.get_axis_list("sep", 0), axis_name="sep")

    def get_check_parallel_group(self, *a, **k):
        from .communication.group import Group
        return Group(list(range(self._topo.world_size())), axis_name=None)
