"""Distributed checkpoint load with reshard-on-load (reference:
python/paddle/distributed/checkpoint/load_state_dict.py:526): reassembles
global tensors from shard files, then re-places them under the current
mesh/sharding of the destination state_dict — resumable across changed
parallelism degrees.
"""
from __future__ import annotations

import glob
import json
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["load_state_dict"]


def _flat_targets(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat_targets(v, key))
        else:
            out[key] = v
    return out


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    data = {}
    for fn in glob.glob(os.path.join(path, "rank*.data")):
        with open(fn, "rb") as f:
            data.update(pickle.load(f))
    targets = _flat_targets(state_dict)
    for name, t in targets.items():
        entry = meta["tensors"].get(name)
        if entry is None or entry.get("py"):
            continue
        full = np.zeros(entry["shape"], dtype=entry["dtype"] if entry["dtype"] != "bfloat16"
                        else np.float32)
        for sid, shard in enumerate(entry["shards"]):
            arr = data.get((name, sid))
            if arr is None:
                continue
            idx = tuple(slice(a, b) for a, b in shard["index"])
            full[idx] = np.asarray(arr, dtype=full.dtype)
        if isinstance(t, Tensor):
            v = jnp.asarray(full, dtype=t._value.dtype)
            try:
                sh = t._value.sharding
                v = jax.device_put(v, sh)  # reshard to destination placement
            except Exception:
                pass
            t._set_value(v)
    return state_dict
