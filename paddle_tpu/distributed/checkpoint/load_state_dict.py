"""Distributed checkpoint load with reshard-on-load (reference:
python/paddle/distributed/checkpoint/load_state_dict.py:526): reassembles
global tensors from shard files, then re-places them under the current
mesh/sharding of the destination state_dict — resumable across changed
parallelism degrees.

Shard payloads are keyed by (name, global extent) so files from different
ranks never collide (multi-host safe; see save_state_dict.py).
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["load_state_dict"]


def _flat_targets(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat_targets(v, key))
        else:
            out[key] = v
    return out


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    # read only the shard files metadata references (never stray rank files
    # left behind by an older save into the same directory)
    referenced = set()
    for entry in meta["tensors"].values():
        for s in entry.get("shards", []) if not entry.get("py") else []:
            referenced.add(s["file"])
    data = {}
    for base in sorted(referenced):
        fn = os.path.join(path, base)
        with open(fn, "rb") as f:
            payload = pickle.load(f)
        for key, arr in payload.items():
            data.setdefault(key, arr)  # replicated extents: first copy wins
    targets = _flat_targets(state_dict)
    for name, t in targets.items():
        entry = meta["tensors"].get(name)
        if entry is None or entry.get("py"):
            continue
        np_dtype = entry["dtype"]
        if np_dtype == "bfloat16":
            np_dtype = "float32"  # assemble in fp32, cast on device_put
        full = np.zeros(entry["shape"], dtype=np_dtype)
        filled = np.zeros(entry["shape"], dtype=bool) if entry["shape"] else None
        for sid, shard in enumerate(entry["shards"]):
            ext = tuple(tuple(p) for p in shard["index"])
            arr = data.get((name, ext))
            if arr is None:
                # version-1 files keyed the payload by rank-local sid
                arr = data.get((name, sid))
            if arr is None:
                continue  # detected below by the completeness check
            idx = tuple(slice(a, b) for a, b in shard["index"])
            full[idx] = np.asarray(jax.device_get(arr), dtype=full.dtype)
            if filled is not None:
                filled[idx] = True
        if filled is not None and not filled.all():
            raise RuntimeError(
                f"checkpoint shard(s) missing for '{name}': only "
                f"{int(filled.sum())}/{filled.size} elements present in "
                f"{path} — incomplete save or mismatched rank files")
        if isinstance(t, Tensor):
            v = jnp.asarray(full, dtype=t._value.dtype)
            try:
                sh = t._value.sharding
                v = jax.device_put(v, sh)  # reshard to destination placement
            except Exception:
                pass
            t._set_value(v)
    return state_dict
