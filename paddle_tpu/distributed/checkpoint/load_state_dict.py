"""Distributed checkpoint load with reshard-on-load (reference:
python/paddle/distributed/checkpoint/load_state_dict.py:526): reassembles
global tensors from shard files, then re-places them under the current
mesh/sharding of the destination state_dict — resumable across changed
parallelism degrees.

Shard payloads are keyed by (name, global extent) so files from different
ranks never collide (multi-host safe; see save_state_dict.py).

Crash consistency: checkpoints written by the staged writer carry a per-file
SHA-256 ``manifest.json``; :func:`verify_checkpoint` re-hashes every listed
file and :func:`load_state_dict` refuses manifest mismatches outright — a
torn or bit-flipped snapshot fails loudly instead of resuming training from
silently wrong weights.  Manifest-less directories (pre-manifest saves) still
load for backward compatibility.
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from .save_state_dict import _sha256, recover_interrupted_commit

__all__ = ["load_state_dict", "verify_checkpoint", "CheckpointCorruptError"]


class CheckpointCorruptError(RuntimeError):
    """The checkpoint directory fails manifest verification: files missing,
    truncated, or altered since the manifest was written."""


def _load_manifest(path):
    """Parse ``path``'s manifest; raises CheckpointCorruptError when absent
    or unreadable."""
    if not os.path.isdir(path):
        raise CheckpointCorruptError(f"{path}: not a checkpoint directory")
    man_fn = os.path.join(path, "manifest.json")
    if not os.path.exists(man_fn):
        raise CheckpointCorruptError(
            f"{path}: manifest.json missing — torn, uncommitted, or "
            "pre-manifest checkpoint")
    try:
        with open(man_fn) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{path}: unreadable manifest.json ({e})") from e
    if "metadata.json" not in man.get("files", {}):
        raise CheckpointCorruptError(
            f"{path}: manifest does not cover metadata.json")
    return man


def _verify_file(path, fn, man):
    info = man.get("files", {}).get(fn)
    if info is None:
        raise CheckpointCorruptError(
            f"{path}: {fn} is not covered by the manifest")
    full = os.path.join(path, fn)
    if not os.path.exists(full):
        raise CheckpointCorruptError(
            f"{path}: {fn} listed in manifest but missing on disk")
    try:
        size = os.path.getsize(full)
        digest = _sha256(full)
    except OSError as e:  # unreadable counts as corrupt: discovery must
        raise CheckpointCorruptError(  # skip it, not crash on it
            f"{path}: {fn} unreadable ({e})") from e
    if size != info.get("size"):
        raise CheckpointCorruptError(
            f"{path}: {fn} size {size} != manifest {info.get('size')} "
            "(truncated or torn write)")
    if digest != info.get("sha256"):
        raise CheckpointCorruptError(
            f"{path}: {fn} sha256 mismatch vs manifest — shard data "
            "missing, torn, or altered")


def verify_checkpoint(path):
    """Verify EVERY manifest-listed file of ``path``; returns the manifest.

    Raises :class:`CheckpointCorruptError` when the manifest is absent,
    unreadable, or any listed file is missing / wrong size / wrong SHA-256 —
    i.e. for every torn-write shape the staged writer can leave behind short
    of a committed rename.  (load_state_dict verifies only the files it
    actually reads — this full pass is for snapshot discovery, e.g.
    CheckpointManager.find_latest_complete.)"""
    recover_interrupted_commit(path)
    man = _load_manifest(path)
    for fn in man.get("files", {}):
        _verify_file(path, fn, man)
    return man


def _flat_targets(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat_targets(v, key))
        else:
            out[key] = v
    return out


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    recover_interrupted_commit(path)
    # verify ONLY what this load reads (manifest-covered metadata + the
    # referenced shard files): a full-directory pass would make every rank
    # re-hash every other rank's payload on the restart critical path
    man = None
    if os.path.exists(os.path.join(path, "manifest.json")):
        man = _load_manifest(path)
        _verify_file(path, "metadata.json", man)
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    # read only the shard files metadata references (never stray rank files
    # left behind by an older save into the same directory)
    referenced = set()
    for entry in meta["tensors"].values():
        for s in entry.get("shards", []) if not entry.get("py") else []:
            referenced.add(s["file"])
    data = {}
    for base in sorted(referenced):
        if man is not None:
            _verify_file(path, base, man)  # reject torn/altered shards loudly
        fn = os.path.join(path, base)
        with open(fn, "rb") as f:
            payload = pickle.load(f)
        for key, arr in payload.items():
            data.setdefault(key, arr)  # replicated extents: first copy wins
    targets = _flat_targets(state_dict)
    for name, t in targets.items():
        entry = meta["tensors"].get(name)
        if entry is None or entry.get("py"):
            continue
        np_dtype = entry["dtype"]
        if np_dtype == "bfloat16":
            np_dtype = "float32"  # assemble in fp32, cast on device_put
        full = np.zeros(entry["shape"], dtype=np_dtype)
        filled = np.zeros(entry["shape"], dtype=bool) if entry["shape"] else None
        for sid, shard in enumerate(entry["shards"]):
            ext = tuple(tuple(p) for p in shard["index"])
            arr = data.get((name, ext))
            if arr is None:
                # version-1 files keyed the payload by rank-local sid
                arr = data.get((name, sid))
            if arr is None:
                continue  # detected below by the completeness check
            idx = tuple(slice(a, b) for a, b in shard["index"])
            full[idx] = np.asarray(jax.device_get(arr), dtype=full.dtype)
            if filled is not None:
                filled[idx] = True
        if filled is not None and not filled.all():
            raise RuntimeError(
                f"checkpoint shard(s) missing for '{name}': only "
                f"{int(filled.sum())}/{filled.size} elements present in "
                f"{path} — incomplete save or mismatched rank files")
        if isinstance(t, Tensor):
            v = jnp.asarray(full, dtype=t._value.dtype)
            try:
                sh = t._value.sharding
                v = jax.device_put(v, sh)  # reshard to destination placement
            except Exception:
                pass
            t._set_value(v)
    return state_dict
