from .save_state_dict import save_state_dict, wait_async_save  # noqa: F401
from .load_state_dict import (load_state_dict, verify_checkpoint,  # noqa: F401
                              CheckpointCorruptError)
