"""Distributed checkpoint save (reference: python/paddle/distributed/
checkpoint/save_state_dict.py:135): per-rank shard files + a metadata file
recording global shapes/shardings, enabling reshard-on-load.

TPU-native: each process saves only its addressable shards of each jax.Array
(single-controller saves all shards); metadata stores the PartitionSpec-like
layout so load_state_dict can reassemble and re-place under any target mesh.
"""
from __future__ import annotations

import json
import os
import pickle

import numpy as np
import jax

from ...core.tensor import Tensor

__all__ = ["save_state_dict"]


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    flat = _flat(state_dict)
    rank = jax.process_index()
    meta = {"version": 1, "tensors": {}}
    shards = {}
    for name, t in flat.items():
        if isinstance(t, Tensor):
            v = t._value
        elif isinstance(t, (np.ndarray, jax.Array)):
            v = t
        else:
            meta["tensors"][name] = {"py": True, "value": t} \
                if isinstance(t, (int, float, str, bool, list)) else {"py": True, "value": None}
            continue
        try:
            local_shards = [(s.index, np.asarray(s.data)) for s in
                            getattr(v, "addressable_shards", [])]
        except Exception:
            local_shards = []
        if not local_shards:
            local_shards = [(tuple(slice(None) for _ in np.shape(v)),
                             np.asarray(jax.device_get(v)))]
        entry = {"shape": list(np.shape(v)), "dtype": str(np.asarray(local_shards[0][1]).dtype),
                 "shards": []}
        seen = set()
        for idx, data in local_shards:
            key = tuple((s.start, s.stop) for s in idx)
            if key in seen:
                continue  # replicated copies: save once
            seen.add(key)
            sid = len(entry["shards"])
            entry["shards"].append({"index": [[s.start, s.stop] for s in idx],
                                    "file": f"rank{rank}.data"})
            shards[(name, sid)] = data
        meta["tensors"][name] = entry
    with open(os.path.join(path, f"rank{rank}.data"), "wb") as f:
        pickle.dump({(n, i): d for (n, i), d in shards.items()}, f, protocol=4)
    if rank == coordinator_rank:
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, default=str)
