"""Distributed checkpoint save (reference: python/paddle/distributed/
checkpoint/save_state_dict.py:135): per-rank shard files + a metadata file
recording global shapes/shardings, enabling reshard-on-load.

TPU-native: each process saves only its addressable shards of each jax.Array
(single-controller saves all shards).  Multi-host safety: shard payloads are
keyed by (name, global extent) — never by a rank-local counter — and every
rank writes a sidecar ``rank{r}.meta.json`` describing its shard extents;
after a global barrier the coordinator merges all sidecars into the single
``metadata.json`` (the analog of the reference's cross-rank metadata gather
in save_state_dict).
"""
from __future__ import annotations

import json
import os
import pickle
import threading

import numpy as np
import jax

from ...core.tensor import Tensor

__all__ = ["save_state_dict", "wait_async_save"]

_async_threads: list[threading.Thread] = []


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def _extent_key(index, shape):
    """Normalize a shard index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for s, dim in zip(index, shape):
        start = 0 if s.start is None else int(s.start)
        stop = int(dim) if s.stop is None else int(s.stop)
        out.append((start, stop))
    return tuple(out)


def _barrier():
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_ckpt_save")


def wait_async_save():
    """Block until all pending async checkpoint writes are on disk."""
    global _async_threads
    for t in _async_threads:
        t.join()
    _async_threads = []


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    os.makedirs(path, exist_ok=True)
    flat = _flat(state_dict)
    rank = jax.process_index()
    # per-rank view of the metadata; merged by the coordinator at the end
    local_meta = {"version": 2, "tensors": {}}
    shards = {}
    for name, t in flat.items():
        if isinstance(t, Tensor):
            v = t._value
        elif isinstance(t, (np.ndarray, jax.Array)):
            v = t
        else:
            local_meta["tensors"][name] = {"py": True, "value": t} \
                if isinstance(t, (int, float, str, bool, list)) else {"py": True, "value": None}
            continue
        shape = tuple(np.shape(v))
        try:
            local_shards = [(s.index, s.data) for s in
                            getattr(v, "addressable_shards", [])]
        except Exception:
            local_shards = []
        if not local_shards:
            local_shards = [(tuple(slice(None) for _ in shape), v)]
        entry = {"shape": list(shape), "dtype": str(np.asarray(
            jax.device_get(local_shards[0][1])).dtype), "shards": []}
        seen = set()
        for idx, data in local_shards:
            ext = _extent_key(idx, shape)
            if ext in seen:
                continue  # replicated copies on this rank: save once
            seen.add(ext)
            entry["shards"].append({"index": [[a, b] for a, b in ext],
                                    "file": f"rank{rank}.data"})
            shards[(name, ext)] = np.asarray(jax.device_get(data))
        local_meta["tensors"][name] = entry

    def _write():
        with open(os.path.join(path, f"rank{rank}.data"), "wb") as f:
            pickle.dump(shards, f, protocol=4)
        with open(os.path.join(path, f"rank{rank}.meta.json"), "w") as f:
            json.dump(local_meta, f, default=str)

    if async_save:
        # device_get already happened above; only the host-side serialization
        # and file IO run in the background thread.
        th = threading.Thread(target=_write, daemon=False)
        th.start()
        _async_threads.append(th)
        if jax.process_count() == 1:
            # single-controller: merge metadata after the write completes
            def _finish():
                th.join()
                _merge_metadata(path)
            fin = threading.Thread(target=_finish, daemon=False)
            fin.start()
            _async_threads.append(fin)
            return
        # multi-host async: caller must invoke wait_async_save() before the
        # barrier; fall through to synchronous merge for safety
        th.join()
    else:
        _write()

    _barrier()  # all ranks' sidecars must be on disk before the merge
    if rank == coordinator_rank:
        _merge_metadata(path)
    _barrier()  # nobody returns until metadata.json exists


def _merge_metadata(path):
    """Merge the current world's rank sidecars into the global metadata.json,
    deduplicating replicated extents across ranks (keep the lowest-rank copy).
    Only ranks [0, process_count) are merged, and stale rank files from a
    previous larger-world save into the same directory are removed so a
    subsequent load cannot mix checkpoints."""
    import glob as _glob
    world = jax.process_count()
    merged = {"version": 2, "tensors": {}}
    files = []
    for fn in _glob.glob(os.path.join(path, "rank*.meta.json")):
        r = int(os.path.basename(fn)[4:].split(".")[0])
        if r < world:
            files.append((r, fn))
        else:  # stale sidecar from an older, larger-world save
            for stale in (fn, os.path.join(path, f"rank{r}.data")):
                try:
                    os.remove(stale)
                except OSError:
                    pass
    files = [fn for _, fn in sorted(files)]
    for fn in files:
        with open(fn) as f:
            m = json.load(f)
        for name, entry in m["tensors"].items():
            if entry.get("py"):
                merged["tensors"].setdefault(name, entry)
                continue
            tgt = merged["tensors"].setdefault(
                name, {"shape": entry["shape"], "dtype": entry["dtype"],
                       "shards": []})
            have = {tuple(tuple(p) for p in s["index"]) for s in tgt["shards"]}
            for s in entry["shards"]:
                ext = tuple(tuple(p) for p in s["index"])
                if ext not in have:
                    have.add(ext)
                    tgt["shards"].append(s)
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(merged, f, default=str)
