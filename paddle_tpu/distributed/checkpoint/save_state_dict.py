"""Distributed checkpoint save (reference: python/paddle/distributed/
checkpoint/save_state_dict.py:135): per-rank shard files + a metadata file
recording global shapes/shardings, enabling reshard-on-load.

TPU-native: each process saves only its addressable shards of each jax.Array
(single-controller saves all shards).  Multi-host safety: shard payloads are
keyed by (name, global extent) — never by a rank-local counter — and every
rank writes a sidecar ``rank{r}.meta.json`` describing its shard extents;
after a global barrier the coordinator merges all sidecars into the single
``metadata.json`` (the analog of the reference's cross-rank metadata gather
in save_state_dict).

Crash consistency (the preemption discipline large TPU jobs live on): every
file is staged into ``<path>.tmp`` with chunked writes + fsync, the
coordinator records a per-file SHA-256 ``manifest.json``, and the single
commit point is the atomic rename of the staging dir onto ``<path>``.  A
crash at ANY instant — mid-file, between files, before the manifest, before
the rename — leaves either the previous intact checkpoint or no final dir at
all, never a load-able-but-wrong snapshot.  The writer consults the
``ckpt.write`` / ``ckpt.commit`` fault points (resilience/faults.py) so all
of those crash windows are exercised in CPU tests.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import threading
import time

import numpy as np
import jax

from ...core.tensor import Tensor
from ...resilience.faults import fault_point

__all__ = ["save_state_dict", "wait_async_save", "WRITE_CHUNK"]

# bytes written between ckpt.write fault-point consults (tests shrink this to
# tear tiny files mid-write)
WRITE_CHUNK = 1 << 20

_async_threads: list[threading.Thread] = []
_async_errors: list[BaseException] = []

# per-file SHA-256 recorded WHILE the bytes are written (_write_durable), so
# the manifest never needs a second synchronous read pass over the staged
# payload: {staging_dir: {basename: (hexdigest, size)}}.  Only fully written
# files are recorded — a write torn by an injected ckpt.write fault leaves no
# digest, and the manifest read-fallback (other ranks' files on a shared
# filesystem, which this process never wrote) keeps multi-host saves correct.
_staged_digests: dict[str, dict[str, tuple[str, int]]] = {}
_digest_lock = threading.Lock()


def _flat(state_dict, prefix=""):
    out = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flat(v, key))
        else:
            out[key] = v
    return out


def _extent_key(index, shape):
    """Normalize a shard index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for s, dim in zip(index, shape):
        start = 0 if s.start is None else int(s.start)
        stop = int(dim) if s.stop is None else int(s.stop)
        out.append((start, stop))
    return tuple(out)


def _barrier():
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_ckpt_save")


def _fsync_dir(path):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_durable(fn, data: bytes):
    """Chunked write + fsync, consulting the ckpt.write fault point before
    every chunk — an injected 'raise' tears the file at that byte offset,
    exactly like a preemption mid-write.  The SHA-256 is folded in while
    the chunks stream out and recorded ONLY once the file is complete, so
    the commit-time manifest costs no second read pass over the payload."""
    base = os.path.basename(fn)
    h = hashlib.sha256()
    with open(fn, "wb") as f:
        for off in range(0, len(data), WRITE_CHUNK) or (0,):
            fault_point("ckpt.write", file=base, offset=off)
            chunk = data[off:off + WRITE_CHUNK]
            f.write(chunk)
            h.update(chunk)
        f.flush()
        os.fsync(f.fileno())
    with _digest_lock:
        _staged_digests.setdefault(
            os.path.dirname(os.path.abspath(fn)), {})[base] = (
                h.hexdigest(), len(data))


def _sha256(fn):
    h = hashlib.sha256()
    with open(fn, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _write_manifest(staging):
    """Per-file SHA-256 manifest over everything staged so far; written last,
    so its presence certifies every other file landed completely.

    Digests come from the hash-while-writing record `_write_durable` kept
    (no second read pass over the payload — the old synchronous re-read
    doubled save-path IO); only files this process did NOT write (other
    ranks' shards on a shared filesystem) fall back to reading."""
    key = os.path.abspath(staging)
    with _digest_lock:
        recorded = dict(_staged_digests.get(key, {}))
    files = sorted(fn for fn in os.listdir(staging) if fn != "manifest.json")
    entries = {}
    for fn in files:
        full = os.path.join(staging, fn)
        size = os.path.getsize(full)
        rec = recorded.get(fn)
        if rec is not None and rec[1] == size:
            digest = rec[0]
        else:                          # not written by this process
            digest = _sha256(full)
        entries[fn] = {"sha256": digest, "size": size}
    man = {"version": 1, "files": entries}
    _write_durable(os.path.join(staging, "manifest.json"),
                   json.dumps(man).encode())
    with _digest_lock:
        _staged_digests.pop(key, None)


def wait_async_save():
    """Block until all pending async checkpoint writes are on disk; re-raises
    the first exception raised inside a writer thread (a silently dropped
    failed write would masquerade as a durable checkpoint)."""
    global _async_threads
    for t in _async_threads:
        t.join()
    _async_threads = []
    if _async_errors:
        first = _async_errors[0]
        _async_errors.clear()
        raise first


def recover_interrupted_commit(path):
    """A crash between the commit's two renames leaves the previous intact
    checkpoint stranded at ``<path>.old`` with ``<path>`` missing — restore
    it.  (When ``<path>`` exists, ``.old`` is just pre-rmtree debris.)
    Called by both the saver and the loader, so the window self-heals on the
    first touch after restart."""
    path = os.fspath(path)
    old = path + ".old"
    if not os.path.exists(path) and os.path.isdir(old):
        try:
            os.rename(old, path)
            return True
        except OSError:
            # several ranks can race this recovery on a shared filesystem —
            # losing the rename is fine as long as somebody healed it
            return os.path.exists(path)
    return False


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False, on_phase=None):
    """``on_phase(name, dur_s)``, when given, receives the writer's two
    sub-phase wall durations — ``ckpt.stage`` (chunked fsync'd writes into
    the staging dir) and ``ckpt.commit`` (manifest + atomic rename) — as
    each completes; on the async path it is called from the writer thread.
    It must not raise; a fault-injected phase reports nothing (the span
    the caller holds still closes)."""
    path = os.fspath(path)
    staging = path + ".tmp"
    rank = jax.process_index()
    if rank == coordinator_rank:
        recover_interrupted_commit(path)
        for stale in (staging, path + ".old"):
            shutil.rmtree(stale, ignore_errors=True)
        with _digest_lock:   # digests of a previous torn attempt are stale
            _staged_digests.pop(os.path.abspath(staging), None)
    _barrier()  # nobody writes into staging before the stale sweep
    os.makedirs(staging, exist_ok=True)
    flat = _flat(state_dict)
    # per-rank view of the metadata; merged by the coordinator at the end
    local_meta = {"version": 2, "tensors": {}}
    shards = {}
    for name, t in flat.items():
        if isinstance(t, Tensor):
            v = t._value
        elif isinstance(t, (np.ndarray, jax.Array)):
            v = t
        else:
            local_meta["tensors"][name] = {"py": True, "value": t} \
                if isinstance(t, (int, float, str, bool, list)) else {"py": True, "value": None}
            continue
        shape = tuple(np.shape(v))
        try:
            local_shards = [(s.index, s.data) for s in
                            getattr(v, "addressable_shards", [])]
        except Exception:
            local_shards = []
        if not local_shards:
            local_shards = [(tuple(slice(None) for _ in shape), v)]
        entry = {"shape": list(shape), "dtype": str(np.asarray(
            jax.device_get(local_shards[0][1])).dtype), "shards": []}
        seen = set()
        for idx, data in local_shards:
            ext = _extent_key(idx, shape)
            if ext in seen:
                continue  # replicated copies on this rank: save once
            seen.add(ext)
            entry["shards"].append({"index": [[a, b] for a, b in ext],
                                    "file": f"rank{rank}.data"})
            shards[(name, ext)] = np.asarray(jax.device_get(data))
        local_meta["tensors"][name] = entry

    def _write():
        t0 = time.perf_counter()
        _write_durable(os.path.join(staging, f"rank{rank}.data"),
                       pickle.dumps(shards, protocol=4))
        _write_durable(os.path.join(staging, f"rank{rank}.meta.json"),
                       json.dumps(local_meta, default=str).encode())
        if on_phase is not None:
            on_phase("ckpt.stage", time.perf_counter() - t0)

    def _commit():
        """Merge metadata, write the manifest, then the commit point: rename
        staging onto the final path (the previous checkpoint, if any, stays
        intact until after the new one is durable)."""
        t0 = time.perf_counter()
        _merge_metadata(staging)
        _write_manifest(staging)
        _fsync_dir(staging)
        # the PARENT directory entry for the staging dir must be durable
        # BEFORE the rename: fsyncing only the staging dir persists its
        # contents, not its own name — after a host crash the journal may
        # replay the rename against a directory entry that was never
        # written, losing a fully-written snapshot.  `ckpt.dirsync` lets
        # the chaos harness kill the commit exactly at this window.
        fault_point("ckpt.dirsync", path=path, phase="parent")
        _fsync_dir(os.path.dirname(os.path.abspath(staging)) or ".")
        fault_point("ckpt.commit", path=path, phase="pre")
        old = path + ".old"
        if os.path.exists(path):
            os.rename(path, old)
            # crash HERE strands the previous checkpoint at .old —
            # recover_interrupted_commit() restores it on the next touch
            fault_point("ckpt.commit", path=path, phase="swap")
        os.rename(staging, path)
        shutil.rmtree(old, ignore_errors=True)
        _fsync_dir(os.path.dirname(os.path.abspath(path)) or ".")
        if on_phase is not None:
            on_phase("ckpt.commit", time.perf_counter() - t0)

    if async_save:
        # device_get already happened above; only the host-side serialization
        # and file IO run in the background thread.
        err_box: list[BaseException] = []

        # the writer thread owns the staging files + err_box until join
        def _write_guarded():  # graftlint: owner=worker
            try:
                _write()
            except BaseException as e:  # noqa: BLE001 — re-raised on join
                err_box.append(e)
                _async_errors.append(e)

        th = threading.Thread(target=_write_guarded, daemon=False)
        th.start()
        _async_threads.append(th)
        if jax.process_count() == 1:
            # single-controller: merge + commit after the write completes;
            # a failed write must never be committed (torn staging stays .tmp)
            # commit runs on its own thread strictly AFTER the writer joins
            def _finish():  # graftlint: owner=worker
                th.join()
                if err_box:
                    return
                try:
                    _commit()
                except BaseException as e:  # noqa: BLE001
                    _async_errors.append(e)
            fin = threading.Thread(target=_finish, daemon=False)
            fin.start()
            _async_threads.append(fin)
            return
        # multi-host async: caller must invoke wait_async_save() before the
        # barrier; fall through to synchronous merge for safety
        th.join()
        if err_box:
            # raised to the caller HERE — retract the queued copy so a later
            # wait_async_save() doesn't re-raise an already-handled failure
            try:
                _async_errors.remove(err_box[0])
            except ValueError:
                pass
            try:
                _async_threads.remove(th)
            except ValueError:
                pass
            raise err_box[0]
    else:
        _write()

    _barrier()  # all ranks' sidecars must be on disk before the merge
    if rank == coordinator_rank:
        _commit()
    _barrier()  # nobody returns until the final dir exists


def _merge_metadata(path):
    """Merge the current world's rank sidecars into the global metadata.json,
    deduplicating replicated extents across ranks (keep the lowest-rank copy).
    Only ranks [0, process_count) are merged, and stale rank files from a
    previous larger-world save into the same directory are removed so a
    subsequent load cannot mix checkpoints."""
    import glob as _glob
    world = jax.process_count()
    merged = {"version": 2, "tensors": {}}
    files = []
    for fn in _glob.glob(os.path.join(path, "rank*.meta.json")):
        r = int(os.path.basename(fn)[4:].split(".")[0])
        if r < world:
            files.append((r, fn))
        else:  # stale sidecar from an older, larger-world save
            for stale in (fn, os.path.join(path, f"rank{r}.data")):
                try:
                    os.remove(stale)
                except OSError:
                    pass
    files = [fn for _, fn in sorted(files)]
    for fn in files:
        with open(fn) as f:
            m = json.load(f)
        for name, entry in m["tensors"].items():
            if entry.get("py"):
                merged["tensors"].setdefault(name, entry)
                continue
            tgt = merged["tensors"].setdefault(
                name, {"shape": entry["shape"], "dtype": entry["dtype"],
                       "shards": []})
            have = {tuple(tuple(p) for p in s["index"]) for s in tgt["shards"]}
            for s in entry["shards"]:
                ext = tuple(tuple(p) for p in s["index"])
                if ext not in have:
                    have.add(ext)
                    tgt["shards"].append(s)
    _write_durable(os.path.join(path, "metadata.json"),
                   json.dumps(merged, default=str).encode())
