"""paddle.distributed parity namespace (full inventory: SURVEY.md §2.3)."""
from __future__ import annotations

from .env import init_parallel_env, get_rank, get_world_size, is_initialized
from .parallel import DataParallel, ParallelEnv, spawn
from .communication import (Group, new_group, get_group, destroy_process_group,
                            wait, barrier, get_backend, all_reduce, all_gather,
                            all_gather_object, reduce, broadcast, scatter,
                            reduce_scatter, all_to_all, all_to_all_single,
                            send, recv, isend, irecv, batch_isend_irecv, P2POp,
                            gather, ReduceOp)
from . import topology
from . import quant_collectives
from . import fleet
from . import auto_parallel
from .auto_parallel.api import (shard_tensor, reshard, shard_layer, shard_optimizer,
                                dtensor_from_fn, unshard_dtensor)
from .auto_parallel.process_mesh import ProcessMesh
from .auto_parallel.placement_type import Shard, Replicate, Partial
from . import checkpoint
from .checkpoint.save_state_dict import save_state_dict
from .checkpoint.load_state_dict import load_state_dict
from . import sharding
from . import utils
from . import launch

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "is_initialized",
    "DataParallel", "ParallelEnv", "spawn", "Group", "new_group", "get_group",
    "destroy_process_group", "wait", "barrier", "get_backend", "all_reduce",
    "all_gather", "all_gather_object", "reduce", "broadcast", "scatter",
    "reduce_scatter", "all_to_all", "all_to_all_single", "send", "recv",
    "isend", "irecv", "batch_isend_irecv", "P2POp", "gather", "ReduceOp",
    "fleet", "ProcessMesh", "shard_tensor", "reshard", "shard_layer",
    "shard_optimizer", "Shard", "Replicate", "Partial", "save_state_dict",
    "load_state_dict",
]
