"""Distributed environment (reference: python/paddle/distributed/parallel.py
env handling — PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM set by the launch CLI,
launch/controllers/collective.py:76).

TPU-native: jax's multi-controller runtime. Each process drives its local TPU
chips; `init_parallel_env` maps to `jax.distributed.initialize` (the TCPStore
rendezvous analog — reference store/tcp_store.h:121) using the same PADDLE_*
env contract so the launch CLI works unchanged.
"""
from __future__ import annotations

import os

import jax

_initialized = [False]


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_group_rank(global_rank())
    return global_rank()


def global_rank() -> int:
    if _initialized[0]:
        return jax.process_index()
    return int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    if _initialized[0]:
        return jax.process_count()
    return int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))


def is_initialized() -> bool:
    return _initialized[0]


def init_parallel_env():
    """reference parallel.py:978 init_parallel_env. Single-host multi-chip
    needs no rendezvous (one process drives all chips); multi-host uses the
    coordination service."""
    if _initialized[0]:
        return
    n_procs = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("WORLD_SIZE", 1)))
    if n_procs > 1:
        endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        coord = os.environ.get("PADDLE_MASTER",
                               endpoints.split(",")[0] if endpoints else None)
        rank = int(os.environ.get("PADDLE_TRAINER_ID", os.environ.get("RANK", 0)))
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n_procs, process_id=rank)
        _initialized[0] = True
    else:
        _initialized[0] = True
    from . import topology
    topology.reset_default_mesh()
    return


def parallel_device_count() -> int:
    return jax.device_count()
