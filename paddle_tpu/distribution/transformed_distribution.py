"""TransformedDistribution + Independent (reference:
python/paddle/distribution/transformed_distribution.py, independent.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import op_call
from .distribution import Distribution
from .transform import ChainTransform, Type

__all__ = ["TransformedDistribution", "Independent"]


class TransformedDistribution(Distribution):
    """base distribution pushed through a chain of transforms:
    log p(y) = log p_base(x) - sum fldj(x) with x = inv(y)."""

    def __init__(self, base, transforms):
        if not isinstance(transforms, (list, tuple)):
            raise TypeError("transforms must be a list of Transform")
        self.base = base
        self.transforms = list(transforms)
        chain = ChainTransform(self.transforms)
        if not Type.is_injective(chain._type):
            raise ValueError(
                "TransformedDistribution requires injective transforms")
        self._chain = chain
        shape = base.batch_shape + base.event_shape
        out_shape = chain.forward_shape(shape)
        # event rank: max of what the base owns and what the transform
        # consumes (elementwise transforms have _event_rank 0)
        ev = max(len(base.event_shape), chain._event_rank)
        self._event_rank_td = ev
        super().__init__(out_shape[: len(out_shape) - ev],
                         out_shape[len(out_shape) - ev:])

    def _sample(self, shape, key):
        x = self.base._sample(shape, key)
        return self._chain._forward(x)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        out = op_call("dist_transformed_sample", self._chain._forward, x)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return op_call("dist_transformed_rsample", self._chain._forward, x)

    def log_prob(self, value):
        """log p(y) = log p_base(inv(y)) - fldj(inv(y)), with event-rank
        bookkeeping: base.log_prob already reduces the base's own event
        dims; any dims the transform treats as event beyond that are summed
        out of lp, and a base event rank beyond the transform's elementwise
        ldj is summed out of the ldj."""
        e_chain = self._chain._event_rank
        e_base = len(self.base.event_shape)
        e_td = self._event_rank_td

        def impl(v):
            x = self._chain._inverse(v)
            lp = self.base.log_prob(Tensor(x))
            lp = lp._value if isinstance(lp, Tensor) else lp
            ldj = self._chain._forward_log_det_jacobian(x)
            extra_lp = e_chain - e_base
            if extra_lp > 0:
                lp = jnp.sum(lp, tuple(range(-extra_lp, 0)))
            extra_ldj = e_td - e_chain
            if hasattr(ldj, "ndim") and extra_ldj > 0 and ldj.ndim:
                ldj = jnp.sum(ldj, tuple(range(-extra_ldj, 0)))
            return lp - ldj
        return op_call("dist_transformed_log_prob", impl, value)


class Independent(Distribution):
    """Reinterprets the rightmost `reinterpreted_batch_rank` batch dims of a
    base distribution as event dims (reference independent.py:25)."""

    def __init__(self, base, reinterpreted_batch_rank):
        r = int(reinterpreted_batch_rank)
        if not 0 < r <= len(base.batch_shape):
            raise ValueError(
                "reinterpreted_batch_rank must be in (0, "
                f"{len(base.batch_shape)}], got {r}")
        self.base = base
        self.reinterpreted_batch_rank = r
        nb = len(base.batch_shape) - r
        super().__init__(base.batch_shape[:nb],
                         base.batch_shape[nb:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def _sample(self, shape, key):
        return self.base._sample(shape, key)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)

        def impl(v):
            return jnp.sum(v, tuple(range(-self.reinterpreted_batch_rank, 0)))
        return op_call("dist_independent_log_prob", impl, lp)

    def entropy(self):
        ent = self.base.entropy()

        def impl(v):
            return jnp.sum(v, tuple(range(-self.reinterpreted_batch_rank, 0)))
        return op_call("dist_independent_entropy", impl, ent)
