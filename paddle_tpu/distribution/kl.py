"""kl_divergence + register_kl double-dispatch registry (reference:
python/paddle/distribution/kl.py:52,84 — most-specific-superclass-pair
resolution, plus the Bregman-divergence fallback for exponential families).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..core.tensor import Tensor
from ..core.dispatch import op_call
from .distribution import Distribution, ExponentialFamily
from .distributions import (Normal, Uniform, Bernoulli, Categorical, Beta,
                            Dirichlet, Gamma, Laplace, LogNormal,
                            Exponential, Geometric, Poisson, Cauchy,
                            MultivariateNormal, Binomial,
                            ContinuousBernoulli)

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a KL(p||q) rule for a class pair; dispatch
    picks the most specific registered (super)class pair."""
    if not (issubclass(cls_p, Distribution)
            and issubclass(cls_q, Distribution)):
        raise TypeError("cls_p and cls_q must be subclass of Distribution")

    def deco(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def _dispatch(cls_p, cls_q):
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(cls_p, p) and issubclass(cls_q, q)]
    if not matches:
        raise NotImplementedError(
            f"Can't compute kl_divergence({cls_p.__name__}, "
            f"{cls_q.__name__}); register it with register_kl.")

    def depth(pair):
        p, q = pair
        return cls_p.__mro__.index(p) + cls_q.__mro__.index(q)
    return _REGISTRY[min(matches, key=depth)]


def kl_divergence(p, q):
    return _dispatch(type(p), type(q))(p, q)


# --- closed forms (reference kl.py:181-300) --------------------------------
@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    def impl(lp, sp, lq, sq):
        var_ratio = (sp / sq) ** 2
        t1 = ((lp - lq) / sq) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return op_call("kl_normal_normal", impl, Tensor(p.loc), Tensor(p.scale),
                   Tensor(q.loc), Tensor(q.scale))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    def impl(al, ah, bl, bh):
        out = jnp.log((bh - bl) / (ah - al))
        return jnp.where((bl > al) | (bh < ah), jnp.inf, out)
    return op_call("kl_uniform_uniform", impl, Tensor(p.low), Tensor(p.high),
                   Tensor(q.low), Tensor(q.high))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    def impl(pp, pq):
        return (jsp.xlogy(pp, pp / pq)
                + jsp.xlogy(1 - pp, (1 - pp) / (1 - pq)))
    return op_call("kl_bernoulli_bernoulli", impl, Tensor(p.probs),
                   Tensor(q.probs))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    def impl(pp, pq):
        return jnp.sum(jsp.xlogy(pp, pp / pq), -1)
    return op_call("kl_categorical_categorical", impl, Tensor(p._p),
                   Tensor(q._p))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    def impl(a1, b1, a2, b2):
        s1 = a1 + b1
        return (jsp.betaln(a2, b2) - jsp.betaln(a1, b1)
                + (a1 - a2) * jsp.digamma(a1)
                + (b1 - b2) * jsp.digamma(b1)
                + (a2 - a1 + b2 - b1) * jsp.digamma(s1))
    return op_call("kl_beta_beta", impl, Tensor(p.alpha), Tensor(p.beta),
                   Tensor(q.alpha), Tensor(q.beta))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    def impl(c1, c2):
        s1 = jnp.sum(c1, -1)
        return (jsp.gammaln(s1) - jnp.sum(jsp.gammaln(c1), -1)
                - jsp.gammaln(jnp.sum(c2, -1))
                + jnp.sum(jsp.gammaln(c2), -1)
                + jnp.sum((c1 - c2) * (jsp.digamma(c1)
                                       - jsp.digamma(s1[..., None])), -1))
    return op_call("kl_dirichlet_dirichlet", impl, Tensor(p.concentration),
                   Tensor(q.concentration))


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    def impl(c1, r1, c2, r2):
        return ((c1 - c2) * jsp.digamma(c1) - jsp.gammaln(c1)
                + jsp.gammaln(c2) + c2 * (jnp.log(r1) - jnp.log(r2))
                + c1 * (r2 / r1 - 1))
    return op_call("kl_gamma_gamma", impl, Tensor(p.concentration),
                   Tensor(p.rate), Tensor(q.concentration), Tensor(q.rate))


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    def impl(lp, sp, lq, sq):
        d = jnp.abs(lp - lq)
        return (jnp.log(sq / sp) + d / sq
                + sp / sq * jnp.exp(-d / sp) - 1)
    return op_call("kl_laplace_laplace", impl, Tensor(p.loc),
                   Tensor(p.scale), Tensor(q.loc), Tensor(q.scale))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    # KL is invariant under the shared exp bijection -> underlying normals
    def impl(lp, sp, lq, sq):
        var_ratio = (sp / sq) ** 2
        t1 = ((lp - lq) / sq) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return op_call("kl_lognormal_lognormal", impl, Tensor(p.loc),
                   Tensor(p.scale), Tensor(q.loc), Tensor(q.scale))


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    def impl(r1, r2):
        return jnp.log(r1) - jnp.log(r2) + r2 / r1 - 1
    return op_call("kl_exponential_exponential", impl, Tensor(p.rate),
                   Tensor(q.rate))


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    # E_p[log p_k - log q_k] with E_p[k] = (1-p)/p
    def impl(pp, pq):
        ek = 1 / pp - 1
        return (jsp.xlog1py(ek, -pp) + jnp.log(pp)
                - jsp.xlog1py(ek, -pq) - jnp.log(pq))
    return op_call("kl_geometric_geometric", impl, Tensor(p.probs),
                   Tensor(q.probs))


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    def impl(r1, r2):
        return r1 * (jnp.log(r1) - jnp.log(r2)) - r1 + r2
    return op_call("kl_poisson_poisson", impl, Tensor(p.rate),
                   Tensor(q.rate))


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    def impl(lp, sp, lq, sq):
        return (jnp.log(((sp + sq) ** 2 + (lp - lq) ** 2)
                        / (4 * sp * sq)))
    return op_call("kl_cauchy_cauchy", impl, Tensor(p.loc), Tensor(p.scale),
                   Tensor(q.loc), Tensor(q.scale))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    def impl(lp, tp, lq, tq):
        d = lp.shape[-1]
        logdet_p = jnp.sum(jnp.log(jnp.diagonal(tp, axis1=-2, axis2=-1)), -1)
        logdet_q = jnp.sum(jnp.log(jnp.diagonal(tq, axis1=-2, axis2=-1)), -1)
        m = jax.scipy.linalg.solve_triangular(tq, tp, lower=True)
        tr = jnp.sum(m ** 2, (-2, -1))
        diff = jax.scipy.linalg.solve_triangular(
            tq, (lq - lp)[..., None], lower=True)[..., 0]
        md = jnp.sum(diff ** 2, -1)
        return logdet_q - logdet_p + 0.5 * (tr + md - d)
    return op_call("kl_mvn_mvn", impl, Tensor(p.loc), Tensor(p._tril),
                   Tensor(q.loc), Tensor(q._tril))


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily_expfamily(p, q):
    """Bregman-divergence fallback (reference kl.py:243): valid when p and q
    are the same exponential family."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "Bregman KL fallback needs matching exponential families; "
            f"got {type(p).__name__} vs {type(q).__name__}")
    p_nat = [n.astype(jnp.float32) for n in p._natural_parameters]
    q_nat = [n.astype(jnp.float32) for n in q._natural_parameters]

    def impl(*nats):
        k = len(nats) // 2
        pn, qn = nats[:k], nats[k:]
        lp = p._log_normalizer(*pn)
        lq = q._log_normalizer(*qn)
        grads = jax.grad(lambda *ps: jnp.sum(p._log_normalizer(*ps)),
                         argnums=tuple(range(k)))(*pn)
        out = lq - lp
        for pi, qi, g in zip(pn, qn, grads):
            out = out - (qi - pi) * g
        return out
    return op_call("kl_expfam_expfam", impl,
                   *[Tensor(n) for n in p_nat + q_nat])


@register_kl(Binomial, Binomial)
def _kl_binomial_binomial(p, q):
    if p.total_count != q.total_count:
        raise NotImplementedError(
            "KL between Binomials requires equal total_count")
    n = float(p.total_count)

    def impl(pp, pq):
        return n * (jsp.xlogy(pp, pp / pq)
                    + jsp.xlog1py(1 - pp, -pp)
                    - jsp.xlog1py(1 - pp, -pq))
    return op_call("kl_binomial_binomial", impl, p._pt("probs"),
                   q._pt("probs"))


@register_kl(ContinuousBernoulli, ContinuousBernoulli)
def _kl_contbern_contbern(p, q):
    # E_p[log p - log q] with E_p[x] = mean(pp) derived from the TRACED
    # probs so d KL / d probs is exact (reference kl.py:212)
    def impl(pp, pq):
        logit = lambda t: jnp.log(t) - jnp.log1p(-t)
        mean_p = p._mean_of(pp)
        return (p._log_norm(pp) - q._log_norm(pq)
                + mean_p * (logit(pp) - logit(pq))
                + jnp.log1p(-pp) - jnp.log1p(-pq))
    return op_call("kl_contbern_contbern", impl, p._pt("probs"),
                   q._pt("probs"))
