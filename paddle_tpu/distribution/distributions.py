"""Concrete distributions (reference: python/paddle/distribution/{normal,
uniform,bernoulli,categorical,beta,dirichlet,gamma,laplace,lognormal,
multinomial,exponential,geometric,gumbel,poisson,cauchy,chi2,student_t,
binomial,multivariate_normal}.py).

All math is jnp formulas verified against scipy.stats in the tests; sampling
is jax.random (reparameterized draws use jax's implicit-gradient gamma /
affine transforms, which is strictly more than the reference offers — its
CPU/GPU samplers are not differentiable).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..core.tensor import Tensor
from ..core.dispatch import op_call
from ..core.random import split_key
from .distribution import Distribution, ExponentialFamily, _as_jnp, \
    _sample_shape

__all__ = [
    "Normal", "Uniform", "Bernoulli", "Categorical", "Beta", "Dirichlet",
    "Gamma", "Laplace", "LogNormal", "Multinomial", "Exponential",
    "Geometric", "Gumbel", "Poisson", "Cauchy", "Chi2", "StudentT",
    "Binomial", "MultivariateNormal", "ContinuousBernoulli", "LKJCholesky",
]

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


def _t(v):
    t = Tensor(v)
    t.stop_gradient = True
    return t


def _broadcast(*vals):
    shape = jnp.broadcast_shapes(*[v.shape for v in vals])
    return tuple(jnp.broadcast_to(v, shape) for v in vals)


class Normal(Distribution):
    """N(loc, scale^2) (reference normal.py:43; scale is the STD DEV)."""

    def __init__(self, loc, scale, name=None):
        self._wrap_params(loc=loc, scale=scale)
        self.loc, self.scale = _broadcast(_as_jnp(loc), _as_jnp(scale))
        super().__init__(self.loc.shape, ())

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def variance(self):
        return _t(self.scale ** 2)

    @property
    def stddev(self):
        return _t(self.scale)

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.normal(
            key, shape + self.loc.shape, self.loc.dtype)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        eps = jax.random.normal(split_key(), shape + self.loc.shape,
                                self.loc.dtype)
        return op_call("dist_normal_rsample",
                       lambda l, s: l + s * eps,
                       self._pt("loc"), self._pt("scale"))

    def log_prob(self, value):
        def impl(l, s, v):
            return (-((v - l) ** 2) / (2 * s ** 2) - jnp.log(s)
                    - _HALF_LOG_2PI)
        return op_call("dist_normal_log_prob", impl, self._pt("loc"),
                       self._pt("scale"), value)

    def entropy(self):
        return op_call("dist_normal_entropy",
                       lambda s: 0.5 + _HALF_LOG_2PI + jnp.log(s),
                       self._pt("scale"))

    def cdf(self, value):
        return op_call("dist_normal_cdf",
                       lambda l, s, v: jsp.ndtr((v - l) / s),
                       self._pt("loc"), self._pt("scale"), value)

    def icdf(self, value):
        return op_call("dist_normal_icdf",
                       lambda l, s, v: l + s * jsp.ndtri(v),
                       self._pt("loc"), self._pt("scale"), value)


class Uniform(Distribution):
    """U[low, high) (reference uniform.py:40)."""

    def __init__(self, low, high, name=None):
        self._wrap_params(low=low, high=high)
        self.low, self.high = _broadcast(_as_jnp(low), _as_jnp(high))
        super().__init__(self.low.shape, ())

    @property
    def mean(self):
        return _t((self.low + self.high) / 2)

    @property
    def variance(self):
        return _t((self.high - self.low) ** 2 / 12)

    def _sample(self, shape, key):
        u = jax.random.uniform(key, shape + self.low.shape, self.low.dtype)
        return self.low + (self.high - self.low) * u

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        u = jax.random.uniform(split_key(), shape + self.low.shape,
                               self.low.dtype)
        return op_call("dist_uniform_rsample",
                       lambda lo, hi: lo + (hi - lo) * u,
                       self._pt("low"), self._pt("high"))

    def log_prob(self, value):
        def impl(lo, hi, v):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return op_call("dist_uniform_log_prob", impl, self._pt("low"),
                       self._pt("high"), value)

    def entropy(self):
        return op_call("dist_uniform_entropy",
                       lambda lo, hi: jnp.log(hi - lo),
                       self._pt("low"), self._pt("high"))


class Bernoulli(ExponentialFamily):
    """Bernoulli(probs) over {0, 1} (reference bernoulli.py:38)."""

    def __init__(self, probs, name=None):
        self._wrap_params(probs=probs)
        self.probs = _as_jnp(probs)
        self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        super().__init__(self.probs.shape, ())

    @property
    def mean(self):
        return _t(self.probs)

    @property
    def variance(self):
        return _t(self.probs * (1 - self.probs))

    def _sample(self, shape, key):
        return jax.random.bernoulli(
            key, self.probs, shape + self.probs.shape).astype(jnp.float32)

    def log_prob(self, value):
        def impl(p, v):
            return jsp.xlogy(v, p) + jsp.xlog1py(1 - v, -p)
        return op_call("dist_bernoulli_log_prob", impl, self._pt("probs"),
                       value)

    def entropy(self):
        def impl(p):
            return -(jsp.xlogy(p, p) + jsp.xlog1py(1 - p, -p))
        return op_call("dist_bernoulli_entropy", impl, self._pt("probs"))

    @property
    def _natural_parameters(self):
        return (self.logits,)

    def _log_normalizer(self, x):
        return jnp.log1p(jnp.exp(x))

    @property
    def _mean_carrier_measure(self):
        return 0.0


class Categorical(Distribution):
    """Categorical over the last axis of `logits`, which the reference
    treats as UNNORMALIZED PROBABILITIES (categorical.py:149:
    prob = logits / logits.sum(-1))."""

    def __init__(self, logits, name=None):
        self._wrap_params(logits=logits)
        self.logits = _as_jnp(logits)
        self._p = self.logits / jnp.sum(self.logits, -1, keepdims=True)
        super().__init__(self.logits.shape[:-1], ())

    @property
    def mean(self):  # undefined for categorical; match reference absence
        raise NotImplementedError

    def _sample(self, shape, key):
        return jax.random.categorical(
            key, jnp.log(self._p), axis=-1,
            shape=shape + self.logits.shape[:-1]).astype(jnp.int64)

    @staticmethod
    def _gather(lg, v):
        # normalize INSIDE the op so parameter grads flow through the tape
        p = lg / jnp.sum(lg, -1, keepdims=True)
        vi = v.astype(jnp.int32)
        if p.ndim == 1:
            # single distribution, v is a batch of category ids
            return p[vi]
        return jnp.take_along_axis(p, vi[..., None], -1)[..., 0]

    def probs(self, value):
        return op_call("dist_categorical_probs", self._gather,
                       self._pt("logits"), value)

    def log_prob(self, value):
        def impl(lg, v):
            return jnp.log(self._gather(lg, v))
        return op_call("dist_categorical_log_prob", impl,
                       self._pt("logits"), value)

    def entropy(self):
        def impl(lg):
            p = lg / jnp.sum(lg, -1, keepdims=True)
            return -jnp.sum(jsp.xlogy(p, p), -1)
        return op_call("dist_categorical_entropy", impl, self._pt("logits"))


class Beta(ExponentialFamily):
    """Beta(alpha, beta) (reference beta.py:33)."""

    def __init__(self, alpha, beta, name=None):
        self._wrap_params(alpha=alpha, beta=beta)
        self.alpha, self.beta = _broadcast(_as_jnp(alpha), _as_jnp(beta))
        super().__init__(self.alpha.shape, ())

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _t(self.alpha * self.beta / (s ** 2 * (s + 1)))

    def _sample(self, shape, key):
        k1, k2 = jax.random.split(key)
        ga = jax.random.gamma(k1, self.alpha, shape + self.alpha.shape)
        gb = jax.random.gamma(k2, self.beta, shape + self.beta.shape)
        return ga / (ga + gb)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        k1, k2 = jax.random.split(split_key())

        def impl(a, b):
            ga = jax.random.gamma(k1, a, shape + a.shape)
            gb = jax.random.gamma(k2, b, shape + b.shape)
            return ga / (ga + gb)
        return op_call("dist_beta_rsample", impl, self._pt("alpha"),
                       self._pt("beta"))

    def log_prob(self, value):
        def impl(a, b, v):
            return (jsp.xlogy(a - 1, v) + jsp.xlog1py(b - 1, -v)
                    - jsp.betaln(a, b))
        return op_call("dist_beta_log_prob", impl, self._pt("alpha"),
                       self._pt("beta"), value)

    def entropy(self):
        def impl(a, b):
            s = a + b
            return (jsp.betaln(a, b) - (a - 1) * jsp.digamma(a)
                    - (b - 1) * jsp.digamma(b) + (s - 2) * jsp.digamma(s))
        return op_call("dist_beta_entropy", impl, self._pt("alpha"),
                       self._pt("beta"))


class Dirichlet(ExponentialFamily):
    """Dirichlet(concentration) over the last axis (reference
    dirichlet.py:30)."""

    def __init__(self, concentration, name=None):
        self._wrap_params(concentration=concentration)
        self.concentration = _as_jnp(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return _t(self.concentration
                  / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        a = self.concentration
        return _t(a * (a0 - a) / (a0 ** 2 * (a0 + 1)))

    def _sample(self, shape, key):
        return jax.random.dirichlet(
            key, self.concentration,
            shape + self.concentration.shape[:-1])

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        key = split_key()

        def impl(c):
            return jax.random.dirichlet(key, c, shape + c.shape[:-1])
        return op_call("dist_dirichlet_rsample", impl,
                       self._pt("concentration"))

    def log_prob(self, value):
        def impl(c, v):
            return (jnp.sum(jsp.xlogy(c - 1, v), -1)
                    + jsp.gammaln(jnp.sum(c, -1))
                    - jnp.sum(jsp.gammaln(c), -1))
        return op_call("dist_dirichlet_log_prob", impl,
                       self._pt("concentration"), value)

    def entropy(self):
        def impl(c):
            a0 = jnp.sum(c, -1)
            K = c.shape[-1]
            return (jnp.sum(jsp.gammaln(c), -1) - jsp.gammaln(a0)
                    + (a0 - K) * jsp.digamma(a0)
                    - jnp.sum((c - 1) * jsp.digamma(c), -1))
        return op_call("dist_dirichlet_entropy", impl,
                       self._pt("concentration"))


class Gamma(ExponentialFamily):
    """Gamma(concentration, rate) (reference gamma.py:27)."""

    def __init__(self, concentration, rate, name=None):
        self._wrap_params(concentration=concentration, rate=rate)
        self.concentration, self.rate = _broadcast(
            _as_jnp(concentration), _as_jnp(rate))
        super().__init__(self.concentration.shape, ())

    @property
    def mean(self):
        return _t(self.concentration / self.rate)

    @property
    def variance(self):
        return _t(self.concentration / self.rate ** 2)

    def _sample(self, shape, key):
        return jax.random.gamma(
            key, self.concentration,
            shape + self.concentration.shape) / self.rate

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        key = split_key()

        def impl(c, r):
            return jax.random.gamma(key, c, shape + c.shape) / r
        return op_call("dist_gamma_rsample", impl,
                       self._pt("concentration"), self._pt("rate"))

    def log_prob(self, value):
        def impl(c, r, v):
            return (jsp.xlogy(c, r) + jsp.xlogy(c - 1, v) - r * v
                    - jsp.gammaln(c))
        return op_call("dist_gamma_log_prob", impl,
                       self._pt("concentration"), self._pt("rate"), value)

    def entropy(self):
        def impl(c, r):
            return (c - jnp.log(r) + jsp.gammaln(c)
                    + (1 - c) * jsp.digamma(c))
        return op_call("dist_gamma_entropy", impl,
                       self._pt("concentration"), self._pt("rate"))


class Laplace(Distribution):
    """Laplace(loc, scale) (reference laplace.py:30)."""

    def __init__(self, loc, scale, name=None):
        self._wrap_params(loc=loc, scale=scale)
        self.loc, self.scale = _broadcast(_as_jnp(loc), _as_jnp(scale))
        super().__init__(self.loc.shape, ())

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def variance(self):
        return _t(2 * self.scale ** 2)

    @property
    def stddev(self):
        return _t(math.sqrt(2) * self.scale)

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.laplace(
            key, shape + self.loc.shape, self.loc.dtype)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        eps = jax.random.laplace(split_key(), shape + self.loc.shape,
                                 self.loc.dtype)
        return op_call("dist_laplace_rsample", lambda l, s: l + s * eps,
                       self._pt("loc"), self._pt("scale"))

    def log_prob(self, value):
        def impl(l, s, v):
            return -jnp.abs(v - l) / s - jnp.log(2 * s)
        return op_call("dist_laplace_log_prob", impl, self._pt("loc"),
                       self._pt("scale"), value)

    def entropy(self):
        return op_call("dist_laplace_entropy",
                       lambda s: 1 + jnp.log(2 * s), self._pt("scale"))

    def cdf(self, value):
        def impl(l, s, v):
            z = (v - l) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))
        return op_call("dist_laplace_cdf", impl, self._pt("loc"),
                       self._pt("scale"), value)

    def icdf(self, value):
        def impl(l, s, v):
            a = v - 0.5
            return l - s * jnp.sign(a) * jnp.log1p(-2 * jnp.abs(a))
        return op_call("dist_laplace_icdf", impl, self._pt("loc"),
                       self._pt("scale"), value)


class LogNormal(Distribution):
    """exp(N(loc, scale^2)) (reference lognormal.py:27, a
    TransformedDistribution there; direct closed forms here)."""

    def __init__(self, loc, scale, name=None):
        self._wrap_params(loc=loc, scale=scale)
        self.loc, self.scale = _broadcast(_as_jnp(loc), _as_jnp(scale))
        super().__init__(self.loc.shape, ())

    @property
    def mean(self):
        return _t(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        return _t(jnp.expm1(self.scale ** 2)
                  * jnp.exp(2 * self.loc + self.scale ** 2))

    def _sample(self, shape, key):
        return jnp.exp(self.loc + self.scale * jax.random.normal(
            key, shape + self.loc.shape, self.loc.dtype))

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        eps = jax.random.normal(split_key(), shape + self.loc.shape,
                                self.loc.dtype)
        return op_call("dist_lognormal_rsample",
                       lambda l, s: jnp.exp(l + s * eps),
                       self._pt("loc"), self._pt("scale"))

    def log_prob(self, value):
        def impl(l, s, v):
            lv = jnp.log(v)
            return (-((lv - l) ** 2) / (2 * s ** 2) - jnp.log(s) - lv
                    - _HALF_LOG_2PI)
        return op_call("dist_lognormal_log_prob", impl, self._pt("loc"),
                       self._pt("scale"), value)

    def entropy(self):
        return op_call("dist_lognormal_entropy",
                       lambda l, s: 0.5 + _HALF_LOG_2PI + jnp.log(s) + l,
                       self._pt("loc"), self._pt("scale"))


class Multinomial(Distribution):
    """Multinomial(total_count, probs) over the last axis (reference
    multinomial.py:28)."""

    def __init__(self, total_count, probs, name=None):
        self._wrap_params(probs=probs)
        self.total_count = int(total_count)
        p = _as_jnp(probs)
        self.probs = p / jnp.sum(p, -1, keepdims=True)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, shape, key):
        n = self.total_count
        draws = jax.random.categorical(
            key, jnp.log(self.probs), axis=-1,
            shape=(n,) + shape + self.probs.shape[:-1])
        K = self.probs.shape[-1]
        onehot = jax.nn.one_hot(draws, K, dtype=jnp.float32)
        return jnp.sum(onehot, axis=0)

    def log_prob(self, value):
        def impl(p, v):
            p = p / jnp.sum(p, -1, keepdims=True)
            return (jsp.gammaln(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(jsp.gammaln(v + 1), -1)
                    + jnp.sum(jsp.xlogy(v, p), -1))
        return op_call("dist_multinomial_log_prob", impl, self._pt("probs"),
                       value)

    def entropy(self):
        # no closed form; Monte-Carlo-free upper-bound not in reference —
        # reference also omits entropy for Multinomial
        raise NotImplementedError


class Exponential(ExponentialFamily):
    """Exponential(rate) (reference exponential.py:27)."""

    def __init__(self, rate, name=None):
        self._wrap_params(rate=rate)
        self.rate = _as_jnp(rate)
        super().__init__(self.rate.shape, ())

    @property
    def mean(self):
        return _t(1 / self.rate)

    @property
    def variance(self):
        return _t(1 / self.rate ** 2)

    def _sample(self, shape, key):
        return jax.random.exponential(
            key, shape + self.rate.shape, self.rate.dtype) / self.rate

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        eps = jax.random.exponential(split_key(), shape + self.rate.shape,
                                     self.rate.dtype)
        return op_call("dist_exponential_rsample", lambda r: eps / r,
                       self._pt("rate"))

    def log_prob(self, value):
        return op_call("dist_exponential_log_prob",
                       lambda r, v: jnp.log(r) - r * v,
                       self._pt("rate"), value)

    def entropy(self):
        return op_call("dist_exponential_entropy",
                       lambda r: 1 - jnp.log(r), self._pt("rate"))

    def cdf(self, value):
        return op_call("dist_exponential_cdf",
                       lambda r, v: -jnp.expm1(-r * v),
                       self._pt("rate"), value)


class Geometric(Distribution):
    """Geometric(probs): pmf (1-p)^k p, k = 0, 1, ... (reference
    geometric.py:47 — k failures before the first success)."""

    def __init__(self, probs, name=None):
        self._wrap_params(probs=probs)
        self.probs = _as_jnp(probs)
        super().__init__(self.probs.shape, ())

    @property
    def mean(self):
        return _t((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return _t((1 - self.probs) / self.probs ** 2)

    @property
    def stddev(self):
        return _t(jnp.sqrt(1 - self.probs) / self.probs)

    def _sample(self, shape, key):
        u = jax.random.uniform(key, shape + self.probs.shape)
        return jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs))

    def pmf(self, k):
        return op_call("dist_geometric_pmf",
                       lambda p, v: jnp.exp(jsp.xlog1py(v, -p)) * p,
                       self._pt("probs"), k)

    def log_pmf(self, k):
        return op_call("dist_geometric_log_pmf",
                       lambda p, v: jsp.xlog1py(v, -p) + jnp.log(p),
                       self._pt("probs"), k)

    log_prob = log_pmf

    def entropy(self):
        def impl(p):
            q = 1 - p
            return -(jsp.xlogy(q, q) + jsp.xlogy(p, p)) / p
        return op_call("dist_geometric_entropy", impl, self._pt("probs"))

    def cdf(self, k):
        return op_call("dist_geometric_cdf",
                       lambda p, v: 1 - jnp.exp(jsp.xlog1py(v + 1, -p)),
                       self._pt("probs"), k)


class Gumbel(Distribution):
    """Gumbel(loc, scale) (reference gumbel.py:30)."""

    _EULER = 0.57721566490153286060

    def __init__(self, loc, scale, name=None):
        self._wrap_params(loc=loc, scale=scale)
        self.loc, self.scale = _broadcast(_as_jnp(loc), _as_jnp(scale))
        super().__init__(self.loc.shape, ())

    @property
    def mean(self):
        return _t(self.loc + self._EULER * self.scale)

    @property
    def variance(self):
        return _t(math.pi ** 2 / 6 * self.scale ** 2)

    @property
    def stddev(self):
        return _t(math.pi / math.sqrt(6) * self.scale)

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.gumbel(
            key, shape + self.loc.shape, self.loc.dtype)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        eps = jax.random.gumbel(split_key(), shape + self.loc.shape,
                                self.loc.dtype)
        return op_call("dist_gumbel_rsample", lambda l, s: l + s * eps,
                       self._pt("loc"), self._pt("scale"))

    def log_prob(self, value):
        def impl(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return op_call("dist_gumbel_log_prob", impl, self._pt("loc"),
                       self._pt("scale"), value)

    def entropy(self):
        return op_call("dist_gumbel_entropy",
                       lambda s: jnp.log(s) + 1 + self._EULER,
                       self._pt("scale"))


class Poisson(Distribution):
    """Poisson(rate) (reference poisson.py:27)."""

    def __init__(self, rate, name=None):
        self._wrap_params(rate=rate)
        self.rate = _as_jnp(rate)
        super().__init__(self.rate.shape, ())

    @property
    def mean(self):
        return _t(self.rate)

    @property
    def variance(self):
        return _t(self.rate)

    def _sample(self, shape, key):
        return jax.random.poisson(
            key, self.rate, shape + self.rate.shape).astype(jnp.float32)

    def log_prob(self, value):
        def impl(r, v):
            return jsp.xlogy(v, r) - r - jsp.gammaln(v + 1)
        return op_call("dist_poisson_log_prob", impl, self._pt("rate"),
                       value)

    def entropy(self):
        """Series entropy like the reference (poisson.py entropy sums the
        pmf over a truncated support)."""
        def impl(r):
            n = jnp.arange(0.0, 2048.0)
            shape = (-1,) + (1,) * r.ndim
            lp = jsp.xlogy(n.reshape(shape), r) - r \
                - jsp.gammaln(n.reshape(shape) + 1)
            return -jnp.sum(jnp.exp(lp) * lp, 0)
        return op_call("dist_poisson_entropy", impl, self._pt("rate"))


class Cauchy(Distribution):
    """Cauchy(loc, scale) (reference cauchy.py:27)."""

    def __init__(self, loc, scale, name=None):
        self._wrap_params(loc=loc, scale=scale)
        self.loc, self.scale = _broadcast(_as_jnp(loc), _as_jnp(scale))
        super().__init__(self.loc.shape, ())

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.cauchy(
            key, shape + self.loc.shape, self.loc.dtype)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        eps = jax.random.cauchy(split_key(), shape + self.loc.shape,
                                self.loc.dtype)
        return op_call("dist_cauchy_rsample", lambda l, s: l + s * eps,
                       self._pt("loc"), self._pt("scale"))

    def log_prob(self, value):
        def impl(l, s, v):
            return (-math.log(math.pi) - jnp.log(s)
                    - jnp.log1p(((v - l) / s) ** 2))
        return op_call("dist_cauchy_log_prob", impl, self._pt("loc"),
                       self._pt("scale"), value)

    def entropy(self):
        return op_call("dist_cauchy_entropy",
                       lambda s: jnp.log(4 * math.pi * s),
                       self._pt("scale"))

    def cdf(self, value):
        def impl(l, s, v):
            return jnp.arctan((v - l) / s) / math.pi + 0.5
        return op_call("dist_cauchy_cdf", impl, self._pt("loc"),
                       self._pt("scale"), value)


class Chi2(Gamma):
    """Chi2(df) = Gamma(df/2, 1/2) (reference chi2.py:21)."""

    def __init__(self, df, name=None):
        self.df = _as_jnp(df)
        super().__init__(self.df / 2, jnp.full_like(self.df, 0.5))


class StudentT(Distribution):
    """StudentT(df, loc, scale) (reference student_t.py:27)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self._wrap_params(df=df, loc=loc, scale=scale)
        self.df, self.loc, self.scale = _broadcast(
            _as_jnp(df), _as_jnp(loc), _as_jnp(scale))
        super().__init__(self.df.shape, ())

    @property
    def mean(self):
        return _t(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        v = jnp.where(self.df > 2,
                      self.scale ** 2 * self.df / (self.df - 2), jnp.inf)
        return _t(jnp.where(self.df > 1, v, jnp.nan))

    def _sample(self, shape, key):
        return self.loc + self.scale * jax.random.t(
            key, self.df, shape + self.df.shape)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        key = split_key()

        def impl(df, l, s):
            return l + s * jax.random.t(key, df, shape + df.shape)
        return op_call("dist_studentt_rsample", impl, self._pt("df"),
                       self._pt("loc"), self._pt("scale"))

    def log_prob(self, value):
        def impl(df, l, s, v):
            z = (v - l) / s
            return (jsp.gammaln((df + 1) / 2) - jsp.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z ** 2 / df))
        return op_call("dist_studentt_log_prob", impl, self._pt("df"),
                       self._pt("loc"), self._pt("scale"), value)

    def entropy(self):
        def impl(df, s):
            h = (df + 1) / 2
            return (jnp.log(s) + 0.5 * jnp.log(df) + jsp.betaln(df / 2, 0.5)
                    + h * (jsp.digamma(h) - jsp.digamma(df / 2)))
        return op_call("dist_studentt_entropy", impl, self._pt("df"),
                       self._pt("scale"))


class Binomial(Distribution):
    """Binomial(total_count, probs) (reference binomial.py:27)."""

    def __init__(self, total_count, probs, name=None):
        self._wrap_params(probs=probs)
        self.total_count = int(total_count)
        self.probs = _as_jnp(probs)
        super().__init__(self.probs.shape, ())

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1 - self.probs))

    def _sample(self, shape, key):
        draws = jax.random.bernoulli(
            key, self.probs,
            (self.total_count,) + shape + self.probs.shape)
        return jnp.sum(draws.astype(jnp.float32), 0)

    def log_prob(self, value):
        def impl(p, v):
            n = float(self.total_count)
            return (jsp.gammaln(jnp.asarray(n + 1.0)) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1) + jsp.xlogy(v, p)
                    + jsp.xlog1py(n - v, -p))
        return op_call("dist_binomial_log_prob", impl, self._pt("probs"),
                       value)

    def entropy(self):
        def impl(p):
            n = self.total_count
            k = jnp.arange(0.0, n + 1.0)
            shape = (-1,) + (1,) * p.ndim
            kk = k.reshape(shape)
            lp = (jsp.gammaln(jnp.asarray(n + 1.0)) - jsp.gammaln(kk + 1)
                  - jsp.gammaln(n - kk + 1) + jsp.xlogy(kk, p)
                  + jsp.xlog1py(n - kk, -p))
            return -jnp.sum(jnp.exp(lp) * lp, 0)
        return op_call("dist_binomial_entropy", impl, self._pt("probs"))


class MultivariateNormal(Distribution):
    """MVN(loc, covariance_matrix) (reference multivariate_normal.py:32;
    scale_tril Cholesky parameterization internally)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _as_jnp(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError(
                "exactly one of covariance_matrix / scale_tril required")
        if covariance_matrix is not None:
            self.covariance_matrix = _as_jnp(covariance_matrix)
            self._tril = jnp.linalg.cholesky(self.covariance_matrix)
        else:
            self._tril = _as_jnp(scale_tril)
            self.covariance_matrix = self._tril @ jnp.swapaxes(
                self._tril, -1, -2)
        super().__init__(jnp.broadcast_shapes(
            self.loc.shape[:-1], self._tril.shape[:-2]), self.loc.shape[-1:])

    @property
    def mean(self):
        return _t(self.loc)

    @property
    def variance(self):
        return _t(jnp.diagonal(self.covariance_matrix, axis1=-2, axis2=-1))

    def _sample(self, shape, key):
        eps = jax.random.normal(
            key, shape + self._batch_shape + self._event_shape,
            self.loc.dtype)
        return self.loc + jnp.einsum("...ij,...j->...i", self._tril, eps)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        eps = jax.random.normal(
            split_key(), shape + self._batch_shape + self._event_shape,
            self.loc.dtype)

        def impl(l, tril):
            return l + jnp.einsum("...ij,...j->...i", tril, eps)
        return op_call("dist_mvn_rsample", impl, self._pt("loc"),
                       Tensor(self._tril))

    def log_prob(self, value):
        def impl(l, tril, v):
            d = l.shape[-1]
            diff = v - l
            sol = jax.scipy.linalg.solve_triangular(
                tril, diff[..., None], lower=True)[..., 0]
            m = jnp.sum(sol ** 2, -1)
            logdet = jnp.sum(jnp.log(
                jnp.diagonal(tril, axis1=-2, axis2=-1)), -1)
            return -0.5 * m - logdet - d * _HALF_LOG_2PI
        return op_call("dist_mvn_log_prob", impl, self._pt("loc"),
                       Tensor(self._tril), value)

    def entropy(self):
        def impl(tril):
            d = tril.shape[-1]
            logdet = jnp.sum(jnp.log(
                jnp.diagonal(tril, axis1=-2, axis2=-1)), -1)
            return d * (0.5 + _HALF_LOG_2PI) + logdet
        return op_call("dist_mvn_entropy", impl, Tensor(self._tril))


class ContinuousBernoulli(ExponentialFamily):
    """Continuous Bernoulli on [0, 1] (reference continuous_bernoulli.py:36;
    Loaiza-Ganem & Cunningham 2019). log C(λ) uses the Taylor expansion in
    the numerically-degenerate window around λ=0.5."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self._wrap_params(probs=probs)
        self.probs = _as_jnp(probs)
        self._lims = lims
        super().__init__(self.probs.shape, ())

    @staticmethod
    def _outside(p, lims):
        return (p < lims[0]) | (p > lims[1])

    def _log_norm(self, p):
        # log C(λ) = log|2 artanh(1-2λ)| - log|1-2λ|  (λ != 1/2)
        psafe = jnp.where(self._outside(p, self._lims), p, 0.25)
        out = jnp.log(jnp.abs(jnp.log1p(-psafe) - jnp.log(psafe))) \
            - jnp.log(jnp.abs(1 - 2 * psafe))
        x = p - 0.5
        taylor = jnp.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x * x) * x * x
        return jnp.where(self._outside(p, self._lims), out, taylor)

    def _mean_of(self, p):
        psafe = jnp.where(self._outside(p, self._lims), p, 0.25)
        m = psafe / (2 * psafe - 1) \
            + 1 / (jnp.log1p(-psafe) - jnp.log(psafe))
        x = p - 0.5
        taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x * x) * x
        return jnp.where(self._outside(p, self._lims), m, taylor)

    @property
    def mean(self):
        return _t(self._mean_of(self.probs))

    @property
    def variance(self):
        p = self.probs
        psafe = jnp.where(self._outside(p, self._lims), p, 0.25)
        v = psafe * (psafe - 1) / (1 - 2 * psafe) ** 2 \
            + 1 / (jnp.log1p(-psafe) - jnp.log(psafe)) ** 2
        x = (p - 0.5) ** 2
        taylor = 1.0 / 12.0 - (1.0 / 15.0 - 128.0 / 945.0 * x) * x
        return _t(jnp.where(self._outside(p, self._lims), v, taylor))

    def _icdf(self, p, u):
        psafe = jnp.where(self._outside(p, self._lims), p, 0.25)
        x = (jnp.log1p(u * (2 * psafe - 1) / (1 - psafe))
             / (jnp.log(psafe) - jnp.log1p(-psafe)))
        return jnp.where(self._outside(p, self._lims), x, u)

    def _sample(self, shape, key):
        u = jax.random.uniform(key, shape + self.probs.shape)
        return self._icdf(self.probs, u)

    def rsample(self, shape=()):
        shape = _sample_shape(shape)
        u = jax.random.uniform(split_key(), shape + self.probs.shape)
        return op_call("dist_contbern_rsample",
                       lambda p: self._icdf(p, u), self._pt("probs"))

    def log_prob(self, value):
        def impl(p, v):
            return (jsp.xlogy(v, p) + jsp.xlog1py(1 - v, -p)
                    + self._log_norm(p))
        return op_call("dist_contbern_log_prob", impl, self._pt("probs"),
                       value)

    def cdf(self, value):
        def impl(p, v):
            psafe = jnp.where(self._outside(p, self._lims), p, 0.25)
            num = (jnp.exp(jsp.xlogy(v, psafe) + jsp.xlog1py(1 - v, -psafe))
                   + psafe - 1)
            c = jnp.where(self._outside(p, self._lims),
                          num / (2 * psafe - 1), v)
            return jnp.clip(c, 0.0, 1.0)
        return op_call("dist_contbern_cdf", impl, self._pt("probs"), value)

    def entropy(self):
        def impl(p):
            # mean derived from the TRACED p: entropy must stay
            # differentiable w.r.t. probs (score-identity terms cancel
            # only when m carries its own dependence on p)
            m = self._mean_of(p)
            return -(jsp.xlogy(m, p) + jsp.xlog1py(1 - m, -p)
                     + self._log_norm(p))
        return op_call("dist_contbern_entropy", impl, self._pt("probs"))

    @property
    def _natural_parameters(self):
        return (jnp.log(self.probs) - jnp.log1p(-self.probs),)

    def _log_normalizer(self, x):
        out = jnp.log(jnp.abs(jnp.expm1(x))) - jnp.log(jnp.abs(x))
        return jnp.where(jnp.abs(x) > 2e-3, out,
                         x / 2 + jnp.log(1 + x * x / 24))

    @property
    def _mean_carrier_measure(self):
        return 0.0


class LKJCholesky(Distribution):
    """LKJ distribution over Cholesky factors of correlation matrices
    (reference python/paddle/distribution/lkj_cholesky.py:128; "Generating
    random correlation matrices based on vines and extended onion method",
    Lewandowski, Kurowicka & Joe 2009).

    dim: correlation-matrix size D; concentration eta > 0 (eta = 1 is
    uniform over correlation matrices).  sample_method: "onion" | "cvine".
    Samples are lower-triangular [.., D, D] Cholesky factors with unit row
    norms; log_prob matches the LKJ density on the Cholesky parametrization.
    """

    def __init__(self, dim=2, concentration=1.0, sample_method="onion",
                 name=None):
        if int(dim) < 2:
            raise ValueError(f"dim must be >= 2, got {dim}")
        if sample_method not in ("onion", "cvine"):
            raise ValueError("sample_method should be 'onion' or 'cvine'")
        self.dim = int(dim)
        self._wrap_params(concentration=concentration)
        self.concentration = _as_jnp(concentration)
        self.sample_method = sample_method
        marginal = self.concentration + 0.5 * (self.dim - 2)
        off = jnp.arange(self.dim - 1, dtype=self.concentration.dtype)
        if sample_method == "onion":
            off = jnp.concatenate([jnp.zeros((1,), off.dtype), off])
            self._beta = Beta(off + 0.5, marginal[..., None] - 0.5 * off)
        else:
            tri = jnp.tril(jnp.broadcast_to(
                0.5 * off, (self.dim - 1, self.dim - 1)))
            rows = jnp.tril_indices(self.dim - 1)
            conc = marginal[..., None] - tri[rows]
            self._beta = Beta(conc, conc)
        super().__init__(self.concentration.shape, (self.dim, self.dim))

    def _onion(self, shape, key):
        kb, kn = jax.random.split(key)
        y = self._beta._sample(shape, kb)[..., None]
        D = self.dim
        u = jax.random.normal(kn, shape + self._batch_shape + (D, D),
                              dtype=y.dtype)
        u = jnp.tril(u, -1)
        norm = jnp.linalg.norm(u, axis=-1, keepdims=True)
        u_hyp = u / jnp.where(norm == 0, 1.0, norm)
        u_hyp = u_hyp.at[..., 0, :].set(0.0)
        w = jnp.sqrt(y) * u_hyp
        tiny = jnp.finfo(w.dtype).tiny
        diag = jnp.sqrt(jnp.clip(1 - jnp.sum(w ** 2, -1), tiny))
        return w + jnp.vectorize(jnp.diag, signature="(k)->(k,k)")(diag)

    def _cvine(self, shape, key):
        beta = self._beta._sample(shape, key)        # [.., D(D-1)/2]
        pc = 2 * beta - 1
        D = self.dim
        rows = jnp.tril_indices(D - 1)
        r = jnp.zeros(shape + self._batch_shape + (D - 1, D - 1), beta.dtype)
        r = r.at[..., rows[0], rows[1]].set(pc)
        tiny = jnp.finfo(beta.dtype).tiny
        # pad into the [D, D] strictly-lower block
        r_full = jnp.zeros(shape + self._batch_shape + (D, D), beta.dtype)
        r_full = r_full.at[..., 1:, :-1].set(r)
        r_full = jnp.clip(r_full, -1 + tiny, 1 - tiny)
        z = r_full ** 2
        cum = jnp.cumprod(jnp.sqrt(1 - z), axis=-1)
        shifted = jnp.concatenate(
            [jnp.ones_like(cum[..., :1]), cum[..., :-1]], axis=-1)
        eye = jnp.eye(D, dtype=beta.dtype)
        return jnp.tril((r_full + eye) * shifted)

    def sample(self, shape=()):
        shape = _sample_shape(shape)
        key = split_key()

        def impl(_c):
            if self.sample_method == "onion":
                return self._onion(shape, key)
            return self._cvine(shape, key)
        out = op_call("dist_lkj_sample", impl, self._pt("concentration"))
        t = Tensor(out._value if isinstance(out, Tensor) else out)
        t.stop_gradient = True
        return t

    def log_prob(self, value):
        D = self.dim

        def impl(conc, v):
            diag = jnp.diagonal(v, axis1=-2, axis2=-1)[..., 1:]
            order = jnp.arange(2, D + 1, dtype=conc.dtype)
            order = 2 * (conc[..., None] - 1) + D - order
            unnorm = jnp.sum(order * jnp.log(diag), -1)
            dm1 = D - 1
            alpha = conc + 0.5 * dm1
            denom = jsp.gammaln(alpha) * dm1
            numer = jsp.multigammaln(alpha - 0.5, dm1)
            pi_const = 0.5 * dm1 * math.log(math.pi)
            return unnorm - (pi_const + numer - denom)
        return op_call("dist_lkj_log_prob", impl,
                       self._pt("concentration"), value)
