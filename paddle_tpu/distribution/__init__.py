"""paddle.distribution parity (reference:
python/paddle/distribution/__init__.py — 27-class family + kl registry +
transforms). Implemented TPU-native over jnp/jax.random/jax.scipy.special;
round-5 adds LKJCholesky (onion + cvine samplers)."""
from .distribution import Distribution, ExponentialFamily
from .distributions import (Normal, Uniform, Bernoulli, Categorical, Beta,
                            Dirichlet, Gamma, Laplace, LogNormal,
                            Multinomial, Exponential, Geometric, Gumbel,
                            Poisson, Cauchy, Chi2, StudentT, Binomial,
                            MultivariateNormal, ContinuousBernoulli,
                            LKJCholesky)
from .transformed_distribution import TransformedDistribution, Independent
from .kl import kl_divergence, register_kl
from . import constraint, variable
from .transform import (Transform, AbsTransform, AffineTransform,
                        ChainTransform, ExpTransform, IndependentTransform,
                        PowerTransform, ReshapeTransform, SigmoidTransform,
                        SoftmaxTransform, StackTransform,
                        StickBreakingTransform, TanhTransform)

__all__ = [
    "Distribution", "ExponentialFamily", "Normal", "Uniform", "Bernoulli",
    "Categorical", "Beta", "Dirichlet", "Gamma", "Laplace", "LogNormal",
    "Multinomial", "Exponential", "Geometric", "Gumbel", "Poisson",
    "Cauchy", "Chi2", "StudentT", "Binomial", "MultivariateNormal",
    "ContinuousBernoulli", "LKJCholesky",
    "TransformedDistribution", "Independent", "kl_divergence", "register_kl",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "constraint", "variable",
]
