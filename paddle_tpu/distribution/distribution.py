"""Distribution base classes (reference:
python/paddle/distribution/distribution.py and exponential_family.py).

TPU-native design: parameters are stored as jnp arrays (broadcast once at
construction), every public method goes through `op_call` so results join
the eager autograd tape, and sampling draws from the framework PRNG
(`core.random.split_key`) so `paddle.seed` governs reproducibility.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call
from ..core.random import split_key

__all__ = ["Distribution", "ExponentialFamily"]


def _as_jnp(v, dtype=None):
    if isinstance(v, Tensor):
        a = v._value
    elif isinstance(v, (int, float)):
        a = jnp.asarray(v, jnp.float32)
    else:
        a = jnp.asarray(v)
    if dtype is not None:
        a = a.astype(dtype)
    if jnp.issubdtype(a.dtype, jnp.integer) or a.dtype == jnp.bool_:
        a = a.astype(jnp.float32)
    return a


def _sample_shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Base class (reference distribution.py:36 Distribution): exposes
    batch_shape/event_shape, sample/rsample, log_prob/prob, entropy, kl."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(int(s) for s in batch_shape)
        self._event_shape = tuple(int(s) for s in event_shape)

    def _wrap_params(self, **kw):
        """Remember the ORIGINAL Tensor arguments so log_prob/rsample/kl
        op_calls join the caller's autograd tape (raw-array attrs keep the
        broadcast values for shape/moment math)."""
        self._orig_params = {k: v for k, v in kw.items()
                             if isinstance(v, Tensor)}

    def _pt(self, name):
        orig = getattr(self, "_orig_params", {})
        if name in orig:
            return orig[name]
        return Tensor(getattr(self, name))

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return op_call("dist_stddev", jnp.sqrt, Tensor(self.variance._value)
                       if isinstance(self.variance, Tensor) else self.variance)

    def sample(self, shape=()):
        """Non-differentiable draw (stop_gradient=True)."""
        out = self._sample(_sample_shape(shape), split_key())
        t = Tensor(out)
        t.stop_gradient = True
        return t

    def rsample(self, shape=()):
        """Reparameterized draw; gradients flow to the parameters."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement rsample")

    def _sample(self, shape, key):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        lp = self.log_prob(value)
        return op_call("dist_prob", jnp.exp, lp)

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self._batch_shape}, "
                f"event_shape={self._event_shape})")


class ExponentialFamily(Distribution):
    """Exponential-family base (reference exponential_family.py:24): provides
    the Bregman-divergence entropy via `_log_normalizer` autodiff — the same
    trick the reference implements with paddle.grad, here with jax.grad."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        """-H = E[log p] via Bregman identity: entropy = A(θ) - <θ, ∇A(θ)>
        + E[carrier] (reference exponential_family.py:48)."""
        nat = [np_.astype(jnp.float32) for np_ in self._natural_parameters]

        def impl(*nat_arrs):
            lognorm = self._log_normalizer(*nat_arrs)
            grads = jax.grad(
                lambda *ps: jnp.sum(self._log_normalizer(*ps)),
                argnums=tuple(range(len(nat_arrs))))(*nat_arrs)
            ent = lognorm - self._mean_carrier_measure
            for p, g in zip(nat_arrs, grads):
                ent = ent - p * g
            return ent
        return op_call("dist_expfam_entropy", impl,
                       *[Tensor(n) for n in nat])
