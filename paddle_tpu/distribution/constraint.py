"""Constraint predicates for random variables (reference:
python/paddle/distribution/constraint.py — the support-validation machinery
`variable.Variable` wires into distributions).  jnp-vectorized: each check
returns an elementwise/reduced boolean array instead of relying on python
chained comparisons."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Constraint", "Real", "Range", "Positive", "Simplex",
           "real", "positive", "simplex", "_v"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class Constraint:
    """Constraint condition for a random variable."""

    def __call__(self, value):
        raise NotImplementedError

    def check(self, value):
        return self(value)


class Real(Constraint):
    def __call__(self, value):
        v = _v(value)
        return Tensor(v == v)


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper
        super().__init__()

    def __call__(self, value):
        v = _v(value)
        return Tensor((_v(self._lower) <= v) & (v <= _v(self._upper)))


class Positive(Constraint):
    def __call__(self, value):
        return Tensor(_v(value) >= 0.0)


class Simplex(Constraint):
    def __call__(self, value):
        v = _v(value)
        return Tensor(jnp.all(v >= 0, axis=-1)
                      & (jnp.abs(v.sum(-1) - 1) < 1e-6))


real = Real()
positive = Positive()
simplex = Simplex()
