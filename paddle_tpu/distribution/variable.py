"""Random-variable metadata (reference:
python/paddle/distribution/variable.py) — pairs a discreteness flag and
event rank with a support Constraint, used by transforms/validation."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import constraint
from .constraint import _v

__all__ = ["Variable", "Real", "Positive", "Independent", "Stack",
           "real", "positive"]


class Variable:
    """Random variable of a probability distribution."""

    def __init__(self, is_discrete=False, event_rank=0, constraint=None):
        self._is_discrete = is_discrete
        self._event_rank = event_rank
        self._constraint = constraint

    @property
    def is_discrete(self):
        return self._is_discrete

    @property
    def event_rank(self):
        return self._event_rank

    def constraint(self, value):
        """Check whether `value` meets this variable's support constraint."""
        assert self._constraint is not None
        return self._constraint.check(value)


class Real(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, constraint.real)


class Positive(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, constraint.positive)


class Independent(Variable):
    """Reinterprets the rightmost batch axes of a variable as event axes."""

    def __init__(self, base: Variable, reinterpreted_batch_rank: int):
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__(base.is_discrete,
                         base.event_rank + reinterpreted_batch_rank)

    def constraint(self, value):
        v = _v(self._base.constraint(value))
        for _ in range(self._reinterpreted_batch_rank):
            v = jnp.all(v, axis=-1)
        return Tensor(v)


class Stack(Variable):
    """A stack of variables along an axis (reference variable.py:100 Stack;
    a negative axis landing inside the event dims bumps the event rank, per
    the reference rule)."""

    def __init__(self, vars, axis=0):
        self._vars = list(vars)
        self._axis = axis
        rank = max(v.event_rank for v in self._vars)
        if self._axis + rank < 0:
            rank += 1
        super().__init__(any(v.is_discrete for v in self._vars), rank)

    def constraint(self, value):
        v = _v(value)
        if not (-v.ndim <= self._axis < v.ndim):
            raise ValueError(
                f"Input dimensions {v.ndim} should be greater than stack "
                f"constraint axis {self._axis}.")
        axis = self._axis % v.ndim
        if v.shape[axis] != len(self._vars):
            raise ValueError(
                f"value has {v.shape[axis]} slices along axis {self._axis} "
                f"but the Stack holds {len(self._vars)} variables")
        parts = [_v(var.constraint(Tensor(jnp.take(v, i, axis=axis))))
                 for i, var in enumerate(self._vars)]
        return Tensor(jnp.stack(parts, axis=axis))


real = Real()
positive = Positive()
