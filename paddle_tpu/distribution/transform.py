"""Random-variable transforms (reference:
python/paddle/distribution/transform.py — Transform base with
forward/inverse/log-det-jacobian and the concrete Abs/Affine/Chain/Exp/
Independent/Power/Reshape/Sigmoid/Softmax/Stack/StickBreaking/Tanh set).

Each transform's math runs through op_call so forward/inverse/ldj join the
eager autograd tape; under jit the same impls stage into XLA.
"""
from __future__ import annotations

import enum
import math
import operator
from functools import reduce

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Type(enum.Enum):
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.INJECTION
    # rank of the event block this transform consumes/produces (0 =
    # elementwise); used by TransformedDistribution's log-det accounting
    _event_rank = 0

    @classmethod
    def _is_injective(cls):
        return Type.is_injective(cls._type)

    def __call__(self, x):
        from .transformed_distribution import TransformedDistribution
        from .distribution import Distribution
        if isinstance(x, Distribution):
            return TransformedDistribution(x, [self])
        if isinstance(x, Transform):
            return ChainTransform([x, self])
        return self.forward(x)

    def forward(self, x):
        return op_call(f"transform_{type(self).__name__}_fwd",
                       self._forward, x)

    def inverse(self, y):
        return op_call(f"transform_{type(self).__name__}_inv",
                       self._inverse, y)

    def forward_log_det_jacobian(self, x):
        if hasattr(self, "_forward_log_det_jacobian"):
            return op_call(f"transform_{type(self).__name__}_fldj",
                           self._forward_log_det_jacobian, x)
        if hasattr(self, "_inverse_log_det_jacobian"):
            def impl(v):
                return -self._inverse_log_det_jacobian(self._forward(v))
            return op_call(f"transform_{type(self).__name__}_fldj", impl, x)
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        if hasattr(self, "_inverse_log_det_jacobian"):
            return op_call(f"transform_{type(self).__name__}_ildj",
                           self._inverse_log_det_jacobian, y)
        if hasattr(self, "_forward_log_det_jacobian"):
            def impl(v):
                return -self._forward_log_det_jacobian(self._inverse(v))
            return op_call(f"transform_{type(self).__name__}_ildj", impl, y)
        raise NotImplementedError

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)


class AbsTransform(Transform):
    """y = |x| (surjective; reference transform.py:372)."""
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return -y, y


class AffineTransform(Transform):
    """y = loc + scale * x (reference transform.py:445)."""
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = loc._value if isinstance(loc, Tensor) else jnp.asarray(loc)
        self.scale = scale._value if isinstance(scale, Tensor) \
            else jnp.asarray(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(
            jnp.log(jnp.abs(self.scale)), jnp.broadcast_shapes(
                x.shape, self.scale.shape)).astype(x.dtype)


class ExpTransform(Transform):
    """y = exp(x) (reference transform.py:657)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    """y = x ** power on the positive half-line (reference
    transform.py:802)."""
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = power._value if isinstance(power, Tensor) \
            else jnp.asarray(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    """y = sigmoid(x) (reference transform.py:995)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    """y = tanh(x) (reference transform.py:1281)."""
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # 2 (log2 - x - softplus(-2x)), the numerically-stable form the
        # reference uses
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """y = softmax(x) over the last axis (reference transform.py:1038;
    not injective — no log-det)."""
    _type = Type.OTHER
    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)

    def forward_shape(self, shape):
        if len(shape) < 1:
            raise ValueError("SoftmaxTransform needs rank >= 1")
        return tuple(shape)

    inverse_shape = forward_shape


class StickBreakingTransform(Transform):
    """R^{K-1} -> simplex^K stick-breaking (reference transform.py:1215)."""
    _type = Type.BIJECTION
    _event_rank = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zc = jnp.cumprod(1 - z, -1)
        lead = jnp.concatenate(
            [jnp.ones_like(zc[..., :1]), zc[..., :-1]], -1)
        y1 = z * lead
        return jnp.concatenate([y1, zc[..., -1:]], -1)

    def _inverse(self, y):
        # x_i = logit(z_i) + log(K - i) with z_i = y_i / stick_before_i,
        # i.e. log y_i - log(stick remaining AFTER i) + log offset
        y_crop = y[..., :-1]
        offset = y_crop.shape[-1] - jnp.arange(
            y_crop.shape[-1], dtype=y.dtype)
        sf_after = 1 - jnp.cumsum(y_crop, -1)
        return jnp.log(y_crop) - jnp.log(sf_after) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zc = jnp.cumprod(1 - z, -1)
        lead = jnp.concatenate(
            [jnp.ones_like(zc[..., :1]), zc[..., :-1]], -1)
        xo = x - jnp.log(offset)
        return jnp.sum(jnp.log(z) - jax.nn.softplus(xo) + jnp.log(lead), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    """Composition t_n ∘ ... ∘ t_1 (reference transform.py:532)."""

    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._type = Type.BIJECTION if all(
            t._type == Type.BIJECTION for t in self.transforms) \
            else Type.INJECTION
        self._event_rank = max(
            (t._event_rank for t in self.transforms), default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        acc = 0.0
        for t in self.transforms:
            if hasattr(t, "_forward_log_det_jacobian"):
                acc = acc + t._forward_log_det_jacobian(x)
            else:
                acc = acc - t._inverse_log_det_jacobian(t._forward(x))
            x = t._forward(x)
        return acc

    def forward_shape(self, shape):
        return reduce(lambda s, t: t.forward_shape(s), self.transforms,
                      tuple(shape))

    def inverse_shape(self, shape):
        return reduce(lambda s, t: t.inverse_shape(s),
                      reversed(self.transforms), tuple(shape))


class IndependentTransform(Transform):
    """Reinterprets the rightmost `reinterpreted_batch_rank` dims as event
    dims: the log-det sums over them (reference transform.py:707)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._type = base._type
        self._event_rank = base._event_rank + self.reinterpreted_batch_rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x) \
            if hasattr(self.base, "_forward_log_det_jacobian") \
            else -self.base._inverse_log_det_jacobian(self.base._forward(x))
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(ldj, axes)

    def forward_shape(self, shape):
        return self.base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self.base.inverse_shape(shape)


class ReshapeTransform(Transform):
    """Reshape the event shape (reference transform.py:869)."""
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._event_rank = len(self.out_event_shape)
        if reduce(operator.mul, self.in_event_shape, 1) != \
                reduce(operator.mul, self.out_event_shape, 1):
            raise ValueError("in/out event sizes must match")

    def _forward(self, x):
        lead = x.shape[: x.ndim - len(self.in_event_shape)]
        return x.reshape(lead + self.out_event_shape)

    def _inverse(self, y):
        lead = y.shape[: y.ndim - len(self.out_event_shape)]
        return y.reshape(lead + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        lead = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(lead, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        if tuple(shape[len(shape) - n:]) != self.in_event_shape:
            raise ValueError("shape mismatch")
        return tuple(shape[: len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        if tuple(shape[len(shape) - n:]) != self.out_event_shape:
            raise ValueError("shape mismatch")
        return tuple(shape[: len(shape) - n]) + self.in_event_shape


class StackTransform(Transform):
    """Apply a sequence of transforms to slices along `axis` (reference
    transform.py:1095)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)
        self._type = Type.BIJECTION if all(
            t._type == Type.BIJECTION for t in self.transforms) \
            else Type.INJECTION

    def _split(self, v):
        return [jnp.squeeze(s, self.axis) for s in
                jnp.split(v, len(self.transforms), self.axis)]

    def _forward(self, x):
        return jnp.stack([t._forward(s) for t, s in
                          zip(self.transforms, self._split(x))], self.axis)

    def _inverse(self, y):
        return jnp.stack([t._inverse(s) for t, s in
                          zip(self.transforms, self._split(y))], self.axis)

    def _forward_log_det_jacobian(self, x):
        outs = []
        for t, s in zip(self.transforms, self._split(x)):
            if hasattr(t, "_forward_log_det_jacobian"):
                outs.append(t._forward_log_det_jacobian(s))
            else:
                outs.append(-t._inverse_log_det_jacobian(t._forward(s)))
        return jnp.stack(outs, self.axis)
