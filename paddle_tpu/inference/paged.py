"""Paged-KV cache manager + continuous-batching decode engine.

The serving-side half of the paged-KV stack (the model math lives in
`models/llama.build_llama_paged_decode`, the attention kernel in
`ops/pallas/paged_attention`).  Reference capability: the Paddle inference
stack's `block_multihead_attention` + fused blockwise KV cache; the TPU
shape follows Ragged Paged Attention (arxiv 2604.15464) + vLLM-style
continuous batching:

  * `PagePool` — fixed-size page allocator over the shared KV page pool
    (free-list alloc/free, double-free/foreign-free guarded).
  * `ServingEngine` — a fixed set of decode SLOTS stepped by ONE jitted
    executable; between steps, finished requests retire (EOS / token
    budget), their pages return to the pool, and queued requests are
    admitted into the freed slots (prefill + first-token sample), so new
    traffic joins a RUNNING batch instead of waiting for the whole batch to
    drain — the throughput win `bench.py serving` measures against the
    static-batch `llama_generate_fused` baseline.

Pages are allocated LAZILY: a request holds ceil(len/page_size) pages at
every moment, growing one page at a time as decode crosses page
boundaries.  If the pool is momentarily empty, the slot simply stalls for
a step (its pending token is masked inactive) until a retirement frees
pages — admission control keeps this rare.

Self-healing (the serving degradation ladder: admit -> queue -> reject ->
preempt):

  * a bounded admission queue rejects overflow with a typed
    `AdmissionRejected` (backpressure) instead of growing unboundedly;
  * per-request deadlines retire overdue work (slot or queue) with
    `Request.timed_out` set, returning its pages;
  * when no slot can make progress (the former hard-deadlock
    RuntimeError), the engine PREEMPTS a victim — the youngest /
    lowest-progress slot — returning its pages and requeueing it at the
    queue head; re-admission re-prefills prompt + already-emitted tokens,
    so greedy outputs stay step-exact vs a never-preempted run;
  * injected page-pool pressure (`serve.pool_pressure` /
    `pagepool.alloc` fault points, resilience/faults.py) exercises all of
    the above deterministically on CPU.
"""
from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..resilience.faults import fault_point

__all__ = ["PagePool", "Request", "ServingEngine", "serve_requests",
           "PoolCapacityError", "AdmissionRejected", "EngineStalledError"]


class PoolCapacityError(ValueError):
    """The request can NEVER fit the configured pool / page-table geometry
    (a sizing error, distinct from malformed input)."""


class AdmissionRejected(RuntimeError):
    """The bounded admission queue is full — backpressure; retry later."""


class EngineStalledError(RuntimeError):
    """run() made no progress for max_stall_steps consecutive steps (only
    reachable under a never-clearing injected pool fault)."""


class PagePool:
    """Fixed-size page allocator (the BlockManager analog): page ids
    0..num_pages-1, LIFO free list for locality, strict double-free /
    foreign-free checks so fragmentation bugs surface immediately."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._allocated = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def alloc(self, n: int):
        """Pop n pages; raises RuntimeError when the pool cannot satisfy the
        request (callers check `num_free` first for graceful stalling).
        Consults the `pagepool.alloc` fault point: a 'trigger' spec forces
        the exhausted path, a 'raise' spec injects InjectedFault."""
        if n < 0:
            raise ValueError("alloc(n): n must be >= 0")
        injected = fault_point("pagepool.alloc", n=n, free=len(self._free))
        if n > len(self._free) or injected is not None:
            raise RuntimeError(
                f"PagePool exhausted{' (injected)' if injected else ''}: "
                f"requested {n} pages, {len(self._free)} "
                f"free of {self.num_pages}")
        pages = [self._free.pop() for _ in range(n)]
        self._allocated.update(pages)
        return pages

    def free(self, pages):
        for p in pages:
            if p not in self._allocated:
                raise RuntimeError(
                    f"PagePool.free: page {p} is not allocated "
                    "(double free or foreign page)")
            self._allocated.remove(p)
            self._free.append(p)


@dataclass
class Request:
    """One serving request: prompt + generation budget + sampling params."""
    rid: int
    prompt: np.ndarray                 # int32 [T]
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_p: float = 1.0
    eos_token_id: int | None = None
    deadline: float | None = None      # absolute perf_counter() cutoff
    # filled by the engine
    generated: list = field(default_factory=list)
    submit_time: float = 0.0
    finish_time: float = 0.0
    timed_out: bool = False            # retired overdue (possibly partial)
    preemptions: int = 0               # times evicted + requeued mid-flight

    @property
    def output_ids(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])


class _Slot:
    __slots__ = ("req", "pages", "pending", "stalled", "admit_seq")

    def __init__(self, req, pages, pending, admit_seq=0):
        self.req = req
        self.pages = pages             # list of physical page ids, in order
        self.pending = pending         # last sampled token, not yet in cache
        self.stalled = False
        self.admit_seq = admit_seq     # monotonically increasing admit order


class ServingEngine:
    """Continuous-batching decode engine over the paged KV cache.

    params: the (embed, block, head) pytrees `build_functional_llama` /
    `functional_params_from_layer` produce.  One jitted decode executable
    covers the whole run; prefill executables are cached per prompt-length
    bucket.
    """

    def __init__(self, params, config, num_slots: int = 4,
                 page_size: int = 16, num_pages: int | None = None,
                 max_pages_per_seq: int | None = None, dtype=None,
                 attention_impl: str = "auto", interpret: bool = False,
                 prompt_bucket: int = 32, decode_horizon: int = 8,
                 seed: int = 0, max_queue: int | None = None):
        import jax
        import jax.numpy as jnp
        from ..models.llama import (build_llama_paged_decode,
                                    _sample_per_request)
        self._jax, self._jnp = jax, jnp
        self.config = config
        self.params = params
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        cap_pages = math.ceil(config.max_position_embeddings / page_size)
        self.max_pages_per_seq = int(max_pages_per_seq or cap_pages)
        if num_pages is None:
            num_pages = self.num_slots * self.max_pages_per_seq
        self.pool = PagePool(num_pages, page_size)
        self.prompt_bucket = int(prompt_bucket)
        self.decode_horizon = max(1, int(decode_horizon))

        init_pages, prefill, decode_step = build_llama_paged_decode(
            config, page_size=page_size, num_pages=num_pages, dtype=dtype,
            attention_impl=attention_impl, interpret=interpret)
        cache = init_pages()
        self._pages_k, self._pages_v = cache["k"], cache["v"]

        # decode HORIZON: K decode+sample steps fused into one fori_loop
        # dispatch (admission/retirement happen between horizons).  The
        # per-token python loop costs ~20 ms of dispatch round-trip on the
        # remote TPU transport (PERF.md §:llama_generate_fused) — K
        # amortizes it K-fold, which is what lets continuous batching beat
        # the single-dispatch static fused baseline.  Per-slot eos/budget
        # freezing inside the horizon mirrors llama_generate_fused's
        # masking, so greedy outputs are step-exact at any K.
        def _horizon(params, toks, lengths, page_tables, pk, pv, active, key,
                     temps, top_ps, remaining, eos_ids, *, K, greedy):
            S = toks.shape[0]
            out = jnp.zeros((S, K), jnp.int32)

            def body(t, carry):
                toks, lengths, pk, pv, done, key, out = carry
                live = ~done
                logits, pk, pv = decode_step(params, toks, lengths,
                                             page_tables, pk, pv, live)
                if greedy:
                    # static fast path when every running request decodes
                    # greedily (the common serving default): skips the
                    # sort/cumsum of the nucleus mask — the same shortcut
                    # _sample_token takes for temperature == 0.0
                    tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    key, sub = jax.random.split(key)
                    tok = _sample_per_request(logits, sub, temps, top_ps)
                tok = jnp.where(done, eos_ids, tok)
                out = out.at[:, t].set(tok)
                lengths = lengths + live.astype(lengths.dtype)
                done = done | ((eos_ids >= 0) & (tok == eos_ids)) \
                    | ((t + 1) >= remaining)
                return (tok, lengths, pk, pv, done, key, out)

            carry = (toks, lengths, pk, pv, ~active, key, out)
            toks, lengths, pk, pv, done, key, out = jax.lax.fori_loop(
                0, K, body, carry)
            return out, lengths, pk, pv

        # prefill + first-token sample fused into ONE dispatch per admission
        # (a separate sample call would double the per-admission round-trips
        # on the remote TPU transport)
        def _prefill_sample(params, ids, true_len, page_row, pk, pv, key,
                            temp, top_p, *, greedy):
            logits, pk, pv = prefill(params, ids, true_len, page_row, pk, pv)
            if greedy:
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                tok = _sample_per_request(logits[None], key, temp[None],
                                          top_p[None])[0]
            return tok, pk, pv

        self._horizon_fn = _horizon
        self._horizon_jit = {}         # (K, greedy) -> jitted horizon
        self._prefill_fn = _prefill_sample
        self._prefill_jit = {}         # (T_bucket, greedy) -> jitted prefill

        # host-side slot state
        S, P = self.num_slots, self.max_pages_per_seq
        self._slots: list[_Slot | None] = [None] * S
        self._page_tables = np.zeros((S, P), np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._temps = np.zeros((S,), np.float32)
        self._top_ps = np.ones((S,), np.float32)
        self._queue: deque[Request] = deque()
        self._finished: dict[int, Request] = {}
        self._next_rid = 0
        self._key = jax.random.PRNGKey(seed)
        self.max_queue = None if max_queue is None else int(max_queue)
        self._admit_seq = 0
        self._pressure = False         # this-step injected pool pressure
        self.steps_run = 0
        self.tokens_generated = 0
        self.preemptions = 0           # victim evictions (self-healing)
        self.timeouts = 0              # deadline retirements
        self.rejections = 0            # AdmissionRejected count

    # -- submission --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32, temperature: float = 0.0,
               top_p: float = 1.0, eos_token_id: int | None = None,
               timeout: float | None = None) -> int:
        """Queue one request.  Raises `PoolCapacityError` for requests that
        can NEVER fit the pool geometry, `AdmissionRejected` when the bounded
        queue is full (backpressure), plain ValueError for malformed input.
        `timeout` (seconds from now) retires the request — wherever it is —
        once overdue, with `Request.timed_out` set."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + int(max_new_tokens)
        if total > self.config.max_position_embeddings:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model context "
                f"{self.config.max_position_embeddings}")
        # the cache holds total-1 tokens (the final sampled token is never
        # written); it must fit this request's page-table row
        need = math.ceil((total - 1) / self.page_size)
        if need > self.max_pages_per_seq:
            raise PoolCapacityError(
                f"request needs {need} pages > "
                f"max_pages_per_seq={self.max_pages_per_seq} "
                f"(prompt {len(prompt)} + max_new_tokens {max_new_tokens})")
        if need > self.pool.num_pages:
            raise PoolCapacityError(
                f"request needs {need} pages but the pool only has "
                f"{self.pool.num_pages} ({self.pool.num_free} free) — raise "
                f"num_pages")
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            self.rejections += 1
            raise AdmissionRejected(
                f"admission queue full ({len(self._queue)}/{self.max_queue} "
                f"waiting, {self.num_active} active) — backpressure, retry "
                f"later")
        rid = self._next_rid
        self._next_rid += 1
        now = time.perf_counter()
        req = Request(rid=rid, prompt=prompt, max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_p=float(top_p),
                      eos_token_id=eos_token_id, submit_time=now,
                      deadline=None if timeout is None else now + float(timeout))
        self._queue.append(req)
        return rid

    # -- internals ---------------------------------------------------------
    def _split_key(self):
        self._key, sub = self._jax.random.split(self._key)
        return sub

    def _avail(self) -> int:
        """Free pages as THIS step sees them: zero while an injected
        `serve.pool_pressure` window is active (exhaustion drills)."""
        return 0 if self._pressure else self.pool.num_free

    def _release_slot(self, s: int):
        slot = self._slots[s]
        self.pool.free(slot.pages)
        self._slots[s] = None
        self._page_tables[s] = 0
        self._lengths[s] = 0
        return slot

    def _finish(self, s: int):
        slot = self._release_slot(s)
        slot.req.finish_time = time.perf_counter()
        self._finished[slot.req.rid] = slot.req

    def _preempt(self, s: int):
        """Victim preemption: return the slot's pages and requeue the request
        at the queue head; re-admission re-prefills prompt + already-emitted
        tokens, so greedy decoding resumes step-exact."""
        slot = self._release_slot(s)
        slot.req.preemptions += 1
        self.preemptions += 1
        self._queue.appendleft(slot.req)

    def _pick_victim(self) -> int:
        """Youngest / lowest-progress victim: fewest emitted tokens, ties
        broken toward the most recent admission (least invested work)."""
        return min((s for s, sl in enumerate(self._slots) if sl is not None),
                   key=lambda s: (len(self._slots[s].req.generated),
                                  -self._slots[s].admit_seq))

    def _retire_overdue(self):
        """Deadline enforcement: retire overdue requests wherever they live
        (running slot or admission queue), marking them timed_out."""
        now = time.perf_counter()
        for s, slot in enumerate(self._slots):
            if slot is not None and slot.req.deadline is not None \
                    and now > slot.req.deadline:
                slot.req.timed_out = True
                self.timeouts += 1
                self._finish(s)
        if any(r.deadline is not None and now > r.deadline
               for r in self._queue):
            keep: deque[Request] = deque()
            for req in self._queue:
                if req.deadline is not None and now > req.deadline:
                    req.timed_out = True
                    req.finish_time = now
                    self.timeouts += 1
                    self._finished[req.rid] = req
                else:
                    keep.append(req)
            self._queue = keep

    def _record_token(self, s: int, tok: int) -> bool:
        """Append a sampled token; returns True when the request finished."""
        slot = self._slots[s]
        req = slot.req
        req.generated.append(int(tok))
        self.tokens_generated += 1
        done = (req.eos_token_id is not None and int(tok) == req.eos_token_id) \
            or len(req.generated) >= req.max_new_tokens
        if done:
            self._finish(s)
        else:
            slot.pending = int(tok)
        return done

    def _admit(self):
        jnp = self._jnp
        while self._queue:
            free_slots = [i for i, sl in enumerate(self._slots) if sl is None]
            if not free_slots:
                return
            req = self._queue[0]
            # resume path (preempted request): the cache must hold prompt +
            # all emitted tokens except the last, which becomes the pending
            # token — exactly the state the victim was evicted in
            resuming = len(req.generated) > 0
            ctx = req.prompt if not resuming else np.concatenate(
                [req.prompt, np.asarray(req.generated[:-1], np.int32)])
            T = len(ctx)
            n_pages = max(1, math.ceil(T / self.page_size))
            if n_pages > self._avail():
                return                 # wait for retirements to free pages
            self._queue.popleft()
            s = free_slots[0]
            pages = self.pool.alloc(n_pages)
            row = np.zeros((self.max_pages_per_seq,), np.int32)
            row[:n_pages] = pages
            # bucketed prompt pad -> one prefill executable per bucket
            # (clamped to the rope-table length: the bucket round-up may
            # overshoot the model context even though the prompt fits)
            Tb = max(self.prompt_bucket,
                     math.ceil(T / self.prompt_bucket) * self.prompt_bucket)
            Tb = min(Tb, self.config.max_position_embeddings)
            ids = np.zeros((1, Tb), np.int32)
            ids[0, :T] = ctx
            greedy = req.temperature <= 0.0
            pf = self._prefill_jit.get((Tb, greedy))
            if pf is None:
                fn = self._prefill_fn
                pf = self._jax.jit(
                    (lambda *a: fn(*a, greedy=True)) if greedy
                    else (lambda *a: fn(*a, greedy=False)),
                    donate_argnums=(4, 5))
                self._prefill_jit[(Tb, greedy)] = pf
            tok, self._pages_k, self._pages_v = pf(
                self.params, jnp.asarray(ids), jnp.asarray(T, jnp.int32),
                jnp.asarray(row), self._pages_k, self._pages_v,
                self._split_key(), jnp.asarray(req.temperature, jnp.float32),
                jnp.asarray(req.top_p, jnp.float32))
            self._slots[s] = _Slot(req, pages, 0, admit_seq=self._admit_seq)
            self._admit_seq += 1
            self._page_tables[s] = row
            self._lengths[s] = T
            self._temps[s] = req.temperature
            self._top_ps[s] = req.top_p
            if resuming:
                # the re-prefill rebuilt the cache; the last emitted token is
                # still the pending one — discard the redundant sample
                self._slots[s].pending = int(req.generated[-1])
            else:
                self._record_token(s, int(np.asarray(tok)))

    def _remaining(self, s: int) -> int:
        req = self._slots[s].req
        return req.max_new_tokens - len(req.generated)

    def _provision(self, steps: int):
        """Lazy page growth for up to `steps` decode steps ahead: every slot
        gets pages covering write positions < lengths + min(steps,
        remaining); a slot the pool cannot fully cover stalls this horizon.
        Returns the list of runnable slot indices."""
        run = []
        for s, slot in enumerate(self._slots):
            if slot is None:
                continue
            slot.stalled = False
            m = min(steps, self._remaining(s))
            need = math.ceil((int(self._lengths[s]) + m) / self.page_size)
            grow = need - len(slot.pages)
            if grow > 0:
                if grow > self._avail():
                    slot.stalled = True
                    continue
                pages = self.pool.alloc(grow)
                start = len(slot.pages)
                slot.pages.extend(pages)
                self._page_tables[s, start:start + grow] = pages
            run.append(s)
        return run

    def _horizon_exec(self, K: int, greedy: bool):
        fn = self._horizon_jit.get((K, greedy))
        if fn is None:
            fn = self._jax.jit(
                lambda *a: self._horizon_fn(*a, K=K, greedy=greedy),
                donate_argnums=(4, 5))
            self._horizon_jit[(K, greedy)] = fn
        return fn

    # -- the serving loop --------------------------------------------------
    @property
    def num_active(self) -> int:
        return sum(1 for sl in self._slots if sl is not None)

    def step(self) -> bool:
        """One engine step: retire overdue requests, admit queued requests
        into free slots, provision pages for the decode horizon, run the
        jitted K-step decode, record sampled tokens, retire finished
        requests.  Returns True when any slot made progress.

        When nobody can progress — the former hard-deadlock RuntimeError —
        the engine self-heals by preempting victims (pages back to the pool,
        request requeued for re-prefill) until a slot can run; under a fully
        injected pool-pressure window it parks and reports no progress."""
        jnp = self._jnp
        self._pressure = fault_point("serve.pool_pressure",
                                     step=self.steps_run) is not None
        self._retire_overdue()
        self._admit()
        K = self.decode_horizon
        run = self._provision(K)
        if not run and K > 1:
            # the pool cannot cover a full horizon for anyone — fall back to
            # single-step pacing so retirements can still free pages
            K = 1
            run = self._provision(1)
        # self-healing: evict ONE victim per no-progress step.  Freed pages
        # go to the stalled SURVIVORS (no re-admission here — the victim at
        # the queue head would immediately steal its own pages back and
        # livelock).  One eviction always suffices for a real deadlock: a
        # stalled slot's single-step growth need is <= 1 page and any victim
        # frees >= 1, so a survivor runs; when it doesn't (an injected
        # pool-pressure window hides every page), per-step budgeting bounds
        # the wasted re-prefills to one victim per stalled step.
        if not run and self.num_active > 0:
            self._preempt(self._pick_victim())
            K = 1
            run = self._provision(1)
        if not run:
            return False               # pool-pressure window or nothing to do
        S = self.num_slots
        active = np.zeros((S,), bool)
        active[run] = True
        toks = np.zeros((S,), np.int32)
        remaining = np.ones((S,), np.int32)
        eos_ids = np.full((S,), -1, np.int32)
        for s in run:
            slot = self._slots[s]
            toks[s] = slot.pending
            remaining[s] = self._remaining(s)
            if slot.req.eos_token_id is not None:
                eos_ids[s] = slot.req.eos_token_id
        greedy = all(self._temps[s] <= 0.0 for s in run)
        out, new_lengths, self._pages_k, self._pages_v = self._horizon_exec(
            K, greedy)(
            self.params, jnp.asarray(toks), jnp.asarray(self._lengths),
            jnp.asarray(self._page_tables), self._pages_k, self._pages_v,
            jnp.asarray(active), self._split_key(),
            jnp.asarray(self._temps), jnp.asarray(self._top_ps),
            jnp.asarray(remaining), jnp.asarray(eos_ids))
        out = np.asarray(out)
        self._lengths = np.asarray(new_lengths).astype(np.int32).copy()
        self.steps_run += 1
        for s in run:
            for tok in out[s]:
                if self._record_token(s, int(tok)):
                    break
        return True

    def run(self, max_steps: int | None = None,
            max_stall_steps: int = 1000):
        """Drive until every submitted request finished; returns
        {rid: Request} (each with .generated / .output_ids filled).

        Consecutive no-progress steps (possible only while an injected
        pool-pressure window hides every page) are bounded by
        `max_stall_steps`; exceeding it raises `EngineStalledError` — the
        pool-sizing deadlock itself is resolved by preemption and can no
        longer raise."""
        steps = 0
        stalled = 0
        while self._queue or self.num_active:
            progressed = self.step()
            stalled = 0 if progressed else stalled + 1
            if stalled >= max_stall_steps:
                raise EngineStalledError(
                    f"no engine progress for {stalled} consecutive steps "
                    f"({self.num_active} active, {len(self._queue)} queued, "
                    f"{self.pool.num_free} pages free of "
                    f"{self.pool.num_pages}) — a fault window that never "
                    f"clears?")
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return dict(self._finished)


def serve_requests(params, config, prompts, **kw):
    """One-shot convenience: submit every (prompt, request-kwargs) pair and
    run to completion.  `prompts` is a list of token arrays or
    (token_array, {request kwargs}) tuples; engine kwargs ride **kw."""
    req_kw_keys = ("max_new_tokens", "temperature", "top_p", "eos_token_id",
                   "timeout")
    default_req = {k: kw.pop(k) for k in req_kw_keys if k in kw}
    eng = ServingEngine(params, config, **kw)
    rids = []
    for p in prompts:
        if isinstance(p, tuple):
            p, rkw = p
            merged = dict(default_req)
            merged.update(rkw)
        else:
            merged = dict(default_req)
        rids.append(eng.submit(p, **merged))
    done = eng.run()
    return [done[r] for r in rids], eng
