"""Paged-KV cache manager + continuous-batching decode engine.

The serving-side half of the paged-KV stack (the model math lives in
`models/llama.build_llama_paged_decode`, the attention kernel in
`ops/pallas/paged_attention`).  Reference capability: the Paddle inference
stack's `block_multihead_attention` + fused blockwise KV cache; the TPU
shape follows Ragged Paged Attention (arxiv 2604.15464) + vLLM-style
continuous batching:

  * `PagePool` — fixed-size REFCOUNTED page allocator over the shared KV
    page pool: `alloc` hands out pages at refcount 1, `share` lets the
    same physical page appear in many page tables (prefix cache), `free`
    decrements and only returns a page to the free list at refcount 0.
    Double frees, foreign pages, and duplicate ids inside one `free()`
    batch raise a typed `PageDoubleFreeError` BEFORE any state mutates.
  * `PrefixCache` — automatic prefix caching: a block-hash index (SHA-256
    of each page_size-aligned token block, chained on the parent block's
    hash, radix-style) mapping prompt prefixes to cached KV pages.
    Finished/preempted requests retire their pages INTO the cache instead
    of freeing them; later admissions attach the longest cached prefix
    read-only and prefill only the suffix.  A partially filled cached
    page is copied before anyone writes into it (copy-on-write).
  * `ServingEngine` — a fixed set of decode SLOTS stepped by ONE jitted
    executable; between steps, finished requests retire (EOS / token
    budget), their pages go to the prefix cache, and queued requests are
    admitted into the freed slots (prefill + first-token sample), so new
    traffic joins a RUNNING batch instead of waiting for the whole batch
    to drain — the throughput win `bench.py serving` measures against the
    static-batch `llama_generate_fused` baseline.  Long prompts prefill
    in fixed `prefill_chunk`-token chunks interleaved with decode
    horizons (chunked prefill), so time-to-first-token for queued short
    requests is bounded instead of head-of-line blocked.  With
    `speculative=K`, a host-side prompt-lookup n-gram index drafts up to
    K continuation tokens per greedy slot and one `verify_step` dispatch
    scores all K+1 positions — the engine accepts the longest matching
    draft prefix plus a bonus token (lossless under greedy sampling by
    construction), multiplying useful tokens per forward pass on
    repetitive/extractive traffic.

Pages are allocated LAZILY: a request holds ceil(len/page_size) pages at
every moment, growing one page at a time as decode crosses page
boundaries.  If the pool is momentarily empty, the engine walks the
serving degradation ladder (below) before stalling the slot for a step.

Self-healing (the serving degradation ladder: admit -> queue -> reject ->
evict cache -> preempt):

  * a bounded admission queue rejects overflow with a typed
    `AdmissionRejected` (backpressure) instead of growing unboundedly;
  * per-request deadlines retire overdue work (slot or queue) with
    `Request.timed_out` set, returning its pages;
  * pool exhaustion first EVICTS unreferenced prefix-cache pages (LRU,
    leaf-first along the hash chain) — cached pages are a performance
    opportunity, never a reason to refuse work;
  * when no slot can make progress even after eviction (the former
    hard-deadlock RuntimeError), the engine PREEMPTS a victim — the
    youngest / lowest-progress slot — returning its pages (via the cache,
    so the re-prefill itself can hit) and requeueing it at the queue
    head; re-admission re-prefills prompt + already-emitted tokens, so
    greedy outputs stay step-exact vs a never-preempted run;
  * injected page-pool pressure (`serve.pool_pressure` /
    `pagepool.alloc` fault points, resilience/faults.py) exercises all of
    the above deterministically on CPU.

Greedy outputs are bit-exact with the prefix cache on vs off (including
across preemption + re-prefill) — `tests/test_prefix_cache.py` asserts
token-for-token equality on every parity scenario.

Double-buffered async host loop (`overlap=True`, ROADMAP item 5): the
engine pipelines host scheduling against device execution at depth 1 —
dispatch N's sampled token / cache-length / budget / done state stays ON
DEVICE (`models/llama.make_paged_decode_horizon`) and feeds dispatch N+1
directly, then dispatch N's emitted tokens drain through ONE batched
fetch while N+1 runs.  EOS / budget / deadline / preemption decisions act
on the drained step with a BOUNDED LAG of one dispatch; budget-predicted
retirements hand their slot to the next admission before their final
tokens even land (`_detach_predicted`), so the lag costs no lane
idleness on budget-bound traffic.  `quiesce()` drains the pipeline to an
exact host-visible step boundary — `snapshot()`, `adopt`-driven routers,
`cancel()`, deadline sweeps of in-flight work, speculative verify
dispatches, and the degradation ladder all quiesce first, so every
existing exactness guarantee (greedy bit-exactness across the prefix
cache / chunked prefill / speculative decoding / preemption /
snapshot-restore / fleet-failover matrix) holds with overlap on.  On the
XLA CPU backend, buffer DONATION pins each dispatch to synchronous
execution (PERF.md §14's caveat, root-caused), so overlap mode trades
the in-place page update for async dispatch there; TPU keeps donation —
its transport is async regardless.

Async streaming (the ROADMAP item-4 front-end seed): `submit(...,
on_token=cb)` fires `cb(tok)` for every emitted token in order — at the
sync boundary in a synchronous engine, at the drain in an overlapped one
— and `Request.stream()` iterates tokens as they drain, driving the
engine until retirement; streamed tokens are exactly the final
`Request.generated` record.

Observability: `ServingEngine(..., telemetry=True)` threads a
`paddle_tpu.observability.Telemetry` through the step loop — request-
lifecycle traces (Chrome/Perfetto-exportable), latency histograms
(TTFT/TPOT/queue/per-phase host timing), and a crash flight recorder that
auto-dumps on stalls, recompile-budget failures, preemption storms, and
injected faults.  Telemetry off (default) is a no-op fast path: one flag
check per hook site, zero per-token work, outputs bit-identical either
way.  All timestamps are host clock reads at EXISTING sync boundaries —
telemetry adds no device round-trips (graftlint SYNC001 stays clean) and
no jitted code (sanitize(0) variant counts unchanged).
"""
from __future__ import annotations

import hashlib
import json
import math
import time
import weakref
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..analysis.sanitize import (RecompileBudgetError, instrument,
                                 jit_cache_size)
from ..observability.telemetry import Telemetry
from ..resilience.faults import InjectedFault, fault_point

__all__ = ["PagePool", "PrefixCache", "Request", "ServingEngine",
           "serve_requests", "PoolCapacityError", "AdmissionRejected",
           "EngineStalledError", "PageDoubleFreeError", "KVHandoffError"]


class PoolCapacityError(ValueError):
    """The request can NEVER fit the configured pool / page-table geometry
    (a sizing error, distinct from malformed input)."""


class AdmissionRejected(RuntimeError):
    """The bounded admission queue is full — backpressure; retry later."""


class EngineStalledError(RuntimeError):
    """run() made no progress for max_stall_steps consecutive steps (only
    reachable under a never-clearing injected pool fault)."""


class PageDoubleFreeError(RuntimeError):
    """free()/share() saw a page holding no reference (double free or
    foreign page), or the same page id twice within one free() batch."""


class KVHandoffError(RuntimeError):
    """An ``export_kv`` packet cannot splice into this engine: mismatched
    page geometry, KV dtype, or tensor-parallel degree.  The caller's
    fallback is re-prefill (``adopt``), which walks the normal admission
    ladder and requantizes/reshards for THIS engine — greedy outputs stay
    bit-exact either way."""


class PagePool:
    """Fixed-size refcounted page allocator (the BlockManager analog):
    page ids 0..num_pages-1, LIFO free list for locality.  `alloc` returns
    pages at refcount 1; `share` lets a page appear in another page table
    (+1); `free` decrements and recycles at 0.  All misuse — double free,
    foreign page, duplicate ids in one batch — raises the typed
    `PageDoubleFreeError` before any state mutates, so fragmentation bugs
    surface immediately and never tear the pool."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._refs: dict[int, int] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        """Pages holding at least one reference."""
        return len(self._refs)

    @property
    def num_referenced(self) -> int:
        """Total references across all page tables + the prefix cache
        (>= num_allocated; the excess is prefix sharing)."""
        return sum(self._refs.values())

    @property
    def _allocated(self):
        # backwards-compatible container view (tests use `p in _allocated`)
        return self._refs.keys()

    def refcount(self, page: int) -> int:
        return self._refs.get(int(page), 0)

    def alloc(self, n: int):
        """Pop n pages at refcount 1; raises RuntimeError when the pool
        cannot satisfy the request (callers check `num_free` first for
        graceful stalling).  Consults the `pagepool.alloc` fault point: a
        'trigger' spec forces the exhausted path, a 'raise' spec injects
        InjectedFault."""
        if n < 0:
            raise ValueError("alloc(n): n must be >= 0")
        injected = fault_point("pagepool.alloc", n=n, free=len(self._free))
        if n > len(self._free) or injected is not None:
            raise RuntimeError(
                f"PagePool exhausted{' (injected)' if injected else ''}: "
                f"requested {n} pages, {len(self._free)} "
                f"free of {self.num_pages}")
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def share(self, pages):
        """+1 reference on each page (it appears in one more page table /
        the prefix cache).  Sharing an unallocated page is typed misuse."""
        pages = [int(p) for p in pages]
        for p in pages:
            if p not in self._refs:
                raise PageDoubleFreeError(
                    f"PagePool.share: page {p} is not allocated")
        for p in pages:
            self._refs[p] += 1
        return pages

    def free(self, pages):
        """-1 reference on each page; a page returns to the free list when
        its last reference drops.  The WHOLE batch is validated before any
        decrement (duplicate ids in one batch, double frees, and foreign
        pages raise `PageDoubleFreeError` with the pool untouched)."""
        pages = [int(p) for p in pages]
        seen = set()
        for p in pages:
            if p in seen:
                raise PageDoubleFreeError(
                    f"PagePool.free: page {p} appears more than once in one "
                    f"free() batch (each reference must be freed by its own "
                    f"holder)")
            seen.add(p)
            if p not in self._refs:
                raise PageDoubleFreeError(
                    f"PagePool.free: page {p} is not allocated "
                    "(double free or foreign page)")
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)


_ROOT = b"\x00root"                   # parent digest of block 0


def _chain_digest(parent: bytes, block) -> bytes:
    """One link of the chained block hash: ``sha256(parent + tokens)``.
    THE block-hash implementation — :class:`PrefixCache` indexing and the
    fleet's prefix-affinity router both route through here, so a
    router-side chain computed from a prompt is bit-identical to the
    cache-side chain the serving replica indexed."""
    return hashlib.sha256(
        parent + np.ascontiguousarray(block, np.int32).tobytes()).digest()


def prefix_chain_hashes(tokens, page_size: int) -> list[bytes]:
    """Chained SHA-256 block-hash digests of every full ``page_size``-
    aligned block of ``tokens``, in chain order (digest i identifies the
    WHOLE prefix through block i, exactly as :class:`PrefixCache` indexes
    it).  The trailing partial block is not hashed — partial tails are
    keyed by exact content, not by chain digest.

    This is the public seam between the cache and the fleet router
    (serving/routing.py): both sides MUST produce identical chains, so
    the affinity lookup finds the replica that actually holds the KV.
    Note :meth:`PrefixCache.lookup` caps its match at ``len(tokens) - 1``
    (one suffix token must remain to prefill); a router mirroring the
    attach behavior passes ``tokens[:-1]``."""
    tokens = np.asarray(tokens, np.int32).reshape(-1)
    ps = int(page_size)
    parent = _ROOT
    out: list[bytes] = []
    for i in range(len(tokens) // ps):
        parent = _chain_digest(parent, tokens[i * ps:(i + 1) * ps])
        out.append(parent)
    return out


class _CacheEntry:
    __slots__ = ("key", "parent", "page", "tokens", "tick", "children")

    def __init__(self, key, parent, page, tokens=None):
        self.key = key                # chained SHA-256 digest (None: partial)
        self.parent = parent          # parent block's digest (or _ROOT)
        self.page = page              # physical page id (cache holds 1 ref)
        self.tokens = tokens          # None for full blocks; bytes for the
        self.tick = 0                 #   partial tail block's token content
        self.children = 0             # cached entries chained under this one


class PrefixCache:
    """Automatic prefix cache: a chained block-hash index over PagePool
    pages (the vLLM automatic-prefix-caching / RadixAttention analog).

    Every page_size-aligned token block hashes as
    ``sha256(parent_digest + block_tokens)`` — chaining makes the digest
    identify the whole prefix, so a dict lookup per block walks the radix
    path without storing a tree.  Entries hold ONE pool reference each;
    `lookup` returns matched pages WITHOUT taking references (callers
    attach via `PagePool.share`).  A retired sequence's trailing partial
    block is indexed too (by parent + exact token content): attaching it
    saves up to page_size-1 more prefill tokens, and because the attaching
    request will WRITE into that page's empty tail, the engine copies it
    first (copy-on-write).

    Eviction is LRU over entries that are pure cache (pool refcount 1)
    and leaves of the hash chain (no cached children) — evicting an inner
    block would strand its descendants unreachable while they still hold
    pages."""

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = int(page_size)
        self._full: dict[bytes, _CacheEntry] = {}
        # partial-tail entries indexed by parent digest, so lookup touches
        # only the tails chained under the matched prefix — never the
        # whole cache (admission is the serving hot path)
        self._partial: dict[bytes, dict[bytes, _CacheEntry]] = {}
        self._tick = 0
        self.insertions = 0
        self.evictions = 0
        # optional ``notify(kind, digests)`` listener (kind "insert" |
        # "evict", digests = full-block chain digests): the fleet router
        # keeps its per-replica cached-chain summary current through this
        # hook.  Partial tails are content-keyed, not chain-keyed, so
        # they never notify — the summary tracks full blocks only.
        self.notify = None

    def __len__(self) -> int:
        return len(self._full) + sum(len(d) for d in self._partial.values())

    def pages(self):
        """Every page the cache holds a reference on (one per entry)."""
        for e in self._full.values():
            yield e.page
        for d in self._partial.values():
            for e in d.values():
                yield e.page

    def _touch(self, e: _CacheEntry):
        self._tick += 1
        e.tick = self._tick

    def _digest(self, parent: bytes, block) -> bytes:
        return _chain_digest(parent, block)

    def chain_digests(self):
        """Every FULL-block chain digest currently indexed (the router-
        summary seed for a replica whose cache was built before the
        listener attached — e.g. a snapshot-restored engine)."""
        return self._full.keys()

    # -- lookup / attach ---------------------------------------------------
    def lookup(self, tokens):
        """Longest cached prefix of `tokens` -> (full_pages, partial).

        full_pages: page ids of the matched full blocks, in order.
        partial: None, or (page_id, m) — a cached partially filled page
        whose first m tokens extend the match (the attaching engine MUST
        copy-on-write it before prefilling into its tail).

        The match is capped at len(tokens)-1 so at least one suffix token
        remains to prefill — its logits feed the first sample.  No
        references are taken; callers `share()` what they attach."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.page_size
        limit = len(tokens) - 1
        parent = _ROOT
        pages = []
        n = 0
        while (n + 1) * ps <= limit:
            key = self._digest(parent, tokens[n * ps:(n + 1) * ps])
            e = self._full.get(key)
            if e is None:
                break
            self._touch(e)
            pages.append(e.page)
            parent = key
            n += 1
        partial = None
        rem = tokens[n * ps:limit]
        if len(rem):
            best_m, best_e = 0, None
            for e in self._partial.get(parent, {}).values():
                et = np.frombuffer(e.tokens, np.int32)
                L = min(len(et), len(rem))
                m = 0
                while m < L and et[m] == rem[m]:
                    m += 1
                if m > best_m:
                    best_m, best_e = m, e
            if best_e is not None:
                self._touch(best_e)
                partial = (best_e.page, best_m)
        return pages, partial

    # -- insertion ---------------------------------------------------------
    def register(self, tokens, pages, with_partial: bool = False):
        """Index this sequence's blocks: every full block always, plus the
        trailing partial block when `with_partial` (retire path — the page
        will receive no more writes).  The cache takes its OWN pool
        reference on each newly inserted page; blocks whose digest is
        already cached are left as-is (first writer wins, the caller's
        duplicate copy stays private)."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.page_size
        parent = _ROOT
        n_full = len(tokens) // ps
        inserted: list[bytes] = []
        for i in range(n_full):
            key = self._digest(parent, tokens[i * ps:(i + 1) * ps])
            e = self._full.get(key)
            if e is None:
                self.pool.share([pages[i]])
                e = _CacheEntry(key, parent, int(pages[i]))
                self._full[key] = e
                if parent in self._full:
                    self._full[parent].children += 1
                self.insertions += 1
                inserted.append(key)
            self._touch(e)
            parent = key
        if inserted and self.notify is not None:
            self.notify("insert", inserted)
        if with_partial:
            tail = np.ascontiguousarray(tokens[n_full * ps:], np.int32)
            if len(tail) and n_full < len(pages):
                tb = tail.tobytes()
                tails = self._partial.setdefault(parent, {})
                if tb not in tails:
                    self.pool.share([pages[n_full]])
                    e = _CacheEntry(None, parent, int(pages[n_full]),
                                    tokens=tb)
                    tails[tb] = e
                    if parent in self._full:
                        self._full[parent].children += 1
                    self.insertions += 1
                    self._touch(e)

    # -- eviction ----------------------------------------------------------
    def _evictable(self):
        for d in self._partial.values():
            for e in d.values():
                if self.pool.refcount(e.page) == 1:
                    yield e
        for e in self._full.values():
            if e.children == 0 and self.pool.refcount(e.page) == 1:
                yield e

    def evict(self, n_pages: int) -> int:
        """Drop up to n_pages LRU cache-only leaf entries, returning their
        pages to the free list; returns how many pages were freed.  Walks
        chains back-to-front across calls: evicting a leaf makes its
        parent a leaf for the next pass of the same call."""
        freed = 0
        while freed < n_pages:
            cand = None
            for e in self._evictable():
                if cand is None or e.tick < cand.tick:
                    cand = e
            if cand is None:
                break
            self._drop(cand)
            freed += 1
        self.evictions += freed
        return freed

    def _drop(self, e: _CacheEntry):
        if e.tokens is None:
            del self._full[e.key]
            if self.notify is not None:
                self.notify("evict", [e.key])
        else:
            tails = self._partial[e.parent]
            del tails[e.tokens]
            if not tails:
                del self._partial[e.parent]
        if e.parent in self._full:
            self._full[e.parent].children -= 1
        self.pool.free([e.page])


class _NgramDraft:
    """Prompt-lookup n-gram draft proposer (self-speculative decoding —
    no draft model, no extra weights): a suffix-match index over this
    request's prompt + emitted tokens.  Each (min_n..max_n)-gram maps to
    the start of its most recent continuation; `propose(k)` returns up to
    k tokens that followed the LONGEST matching suffix n-gram the last
    time it occurred.  When the match runs off the end of the sequence,
    the continuation extrapolates periodically at the match lag — exact
    for cyclic output and free to be wrong otherwise (a rejected draft
    costs nothing extra: the verify dispatch is padded to a static K
    anyway).  Index updates are O(max_n) per emitted token."""

    __slots__ = ("toks", "min_n", "max_n", "_idx")

    def __init__(self, tokens, min_n: int = 1, max_n: int = 3):
        self.min_n, self.max_n = int(min_n), int(max_n)
        self._idx = [dict() for _ in range(self.max_n - self.min_n + 1)]
        self.toks: list[int] = []
        for t in np.asarray(tokens, np.int32).reshape(-1):
            self.append(int(t))

    def append(self, tok: int):
        self.toks.append(int(tok))
        # index the n-grams ending at the PREVIOUS position: deferring the
        # insert by one token means (a) every indexed occurrence has at
        # least one continuation token, and (b) the current suffix can
        # never match itself
        e = len(self.toks) - 1            # continuation start
        if e <= 0:
            return
        for j in range(len(self._idx)):
            n = self.min_n + j
            if e >= n:
                self._idx[j][tuple(self.toks[e - n:e])] = e

    def propose(self, k: int) -> list:
        """Up to k draft tokens continuing the longest-matching suffix
        n-gram's most recent earlier occurrence; [] when nothing matches."""
        if k <= 0:
            return []
        T = len(self.toks)
        for j in range(len(self._idx) - 1, -1, -1):   # longest n first
            n = self.min_n + j
            if T < n:
                continue
            pos = self._idx[j].get(tuple(self.toks[-n:]))
            if pos is None:
                continue
            out = []
            for i in range(k):
                src = pos + i
                # past the end: the sequence "continues" with the lag-
                # periodic extension (out already holds those predictions)
                out.append(self.toks[src] if src < T else out[src - T])
            return out
        return []


@dataclass
class Request:
    """One serving request: prompt + generation budget + sampling params."""
    rid: int
    prompt: np.ndarray                 # int32 [T]
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_p: float = 1.0
    eos_token_id: int | None = None
    deadline: float | None = None      # absolute engine-clock cutoff
                                       #   (time.perf_counter unless a
                                       #   telemetry clock is injected)
    # filled by the engine
    generated: list = field(default_factory=list)
    submit_time: float = 0.0
    admit_time: float = 0.0            # FIRST admission into a slot (0.0
                                       #   until admitted; preserved across
                                       #   preemption re-admissions so
                                       #   queue_time keeps its meaning)
    first_token_time: float = 0.0      # TTFT = first_token_time - submit_time
    finish_time: float = 0.0
    timed_out: bool = False            # retired overdue (possibly partial)
    preemptions: int = 0               # times evicted + requeued mid-flight
    cached_prefix_tokens: int = 0      # prefix-cache tokens attached (total
                                       #   across re-prefills)
    draft_proposed: int = 0            # speculative draft tokens proposed
    draft_accepted: int = 0            #   ... greedy-verified AND emitted
                                       #   (an EOS/budget freeze mid-run
                                       #   discards the tail uncounted)
    trace_id: int | None = None        # fleet-wide stitching id: one per
                                       #   END-TO-END request, shared by the
                                       #   frontend/router/replica trace
                                       #   records across migrations and
                                       #   snapshot restores (observability
                                       #   .distributed.TraceStitcher)
    # async-streaming front end (not serialized; a restored Request
    # streams through a fresh subscription)
    on_token: object | None = field(default=None, repr=False, compare=False)
    _engine: object | None = field(default=None, repr=False, compare=False)

    @property
    def draft_accept_rate(self) -> float:
        """Fraction of this request's proposed draft tokens the verify
        step accepted (0.0 when nothing was ever proposed)."""
        return self.draft_accepted / self.draft_proposed \
            if self.draft_proposed else 0.0

    @property
    def retire_time(self) -> float:
        """When the request left the engine (finish, deadline, or queued
        timeout) — an alias of finish_time that can't drift from it."""
        return self.finish_time

    @property
    def queue_time(self) -> float:
        """Seconds waiting for FIRST admission (0.0 until admitted).
        first_token_time alone never distinguished this wait from prefill:
        ttft == queue_time + prefill_time."""
        return self.admit_time - self.submit_time if self.admit_time else 0.0

    @property
    def ttft(self) -> float:
        """Time to first token, seconds (0.0 until the first token)."""
        return self.first_token_time - self.submit_time \
            if self.first_token_time else 0.0

    @property
    def prefill_time(self) -> float:
        """First-admission prefill latency: ttft minus the queue wait."""
        if not (self.first_token_time and self.admit_time):
            return 0.0
        return self.first_token_time - self.admit_time

    @property
    def tpot(self) -> float:
        """Mean seconds per output token AFTER the first (time-per-output-
        token; 0.0 until retired with >= 2 generated tokens)."""
        n = len(self.generated) - 1
        if n <= 0 or not self.first_token_time or not self.finish_time:
            return 0.0
        return (self.finish_time - self.first_token_time) / n

    @property
    def output_ids(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.generated, np.int32)])

    def stream(self, max_stall_steps: int = 1000,
               cancel_on_close: bool = True):
        """Iterate this request's tokens in emission order, DRIVING the
        owning engine between yields until the request retires (the
        single-threaded analog of an async token stream; fed from the
        overlap drain when the engine is double-buffered).  The streamed
        sequence is exactly the final ``generated`` record — a token is
        yielded once it is host-visible, never re-ordered, never skipped.
        Safe to call after retirement (yields the recorded tokens and
        returns).  Raises :class:`EngineStalledError` after
        ``max_stall_steps`` consecutive no-progress engine steps (only
        reachable under a never-clearing injected fault window).

        A consumer that exits EARLY — ``break``, generator ``close()``,
        or the generator being garbage-collected — CANCELS the request
        (``cancel_on_close=False`` opts out): a disconnected client must
        free its pages mid-decode, not keep a slot decoding to nobody.
        Normal exhaustion retires the request first, so completion never
        cancels anything."""
        i = 0
        stalled = 0
        try:
            while True:
                while i < len(self.generated):
                    yield self.generated[i]
                    i += 1
                if self.finish_time:
                    return
                eng = self._engine() if self._engine is not None else None
                if eng is None:
                    raise RuntimeError(
                        "Request.stream: the owning engine is gone and the "
                        "request never retired")
                # consecutive ENGINE no-progress steps, same as run(): a
                # step that progressed other requests resets the counter
                # even if this request yielded nothing yet
                stalled = 0 if eng.step() else stalled + 1
                if stalled >= max_stall_steps:
                    raise EngineStalledError(
                        f"Request.stream: no engine progress for {stalled} "
                        f"consecutive steps waiting on rid={self.rid}")
        finally:
            if cancel_on_close and not self.finish_time:
                eng = self._engine() if self._engine is not None else None
                if eng is not None:
                    eng.cancel(self.rid)


class _Slot:
    __slots__ = ("req", "pages", "pending", "pending_dev", "stalled",
                 "admit_seq", "prefill_pos", "ctx", "resuming", "chunk_step",
                 "draft", "spec_k")

    def __init__(self, req, pages, pending, admit_seq=0):
        self.req = req
        self.pages = pages             # list of physical page ids, in order
        self.pending = pending         # last sampled token, not yet in cache
        self.pending_dev = None        # overlap mode: the admission-sampled
                                       #   first token, still ON DEVICE and
                                       #   unrecorded (drained later); while
                                       #   a lane rides the device carry,
                                       #   both pending fields are None
        self.stalled = False
        self.admit_seq = admit_seq     # monotonically increasing admit order
        self.prefill_pos = None        # tokens prefilled so far; None once
        self.ctx = None                #   decoding (chunked-prefill state)
        self.resuming = False          # re-admission after preemption
        self.chunk_step = -1           # engine step of the last chunk run
                                       #   (one chunk per slot per step)
        self.draft = None              # _NgramDraft (speculative mode only)
        self.spec_k = 0                # adaptive per-slot draft length


class _LaneRec:
    """One lane of an in-flight decode dispatch: which slot it was
    dispatched for, whether the drain must also record the slot's
    admission-deferred first token, and — for budget-predicted
    retirements whose slot was already handed to a successor — the
    detached retirement state (`retiring` + the cache length the
    predecessor had when it was detached)."""
    __slots__ = ("s", "slot", "take_first", "retiring", "base_len")

    def __init__(self, s, slot, take_first):
        self.s = s
        self.slot = slot
        self.take_first = take_first
        self.retiring = False
        self.base_len = 0


class _Inflight:
    """One double-buffered decode dispatch in flight: the un-fetched
    device outputs (``out`` plus the carried token/length/budget/done
    state the NEXT dispatch consumes directly), the lane records the
    drain will replay, and ``srcs`` — slot identity per lane at dispatch
    time, so the next dispatch only carries lanes whose slot is unchanged
    (a retired/preempted/re-admitted lane falls back to host state).
    In overlap mode the dispatch itself runs on the engine's one-worker
    thread and ``fut`` holds its pending result; ``ServingEngine._resolve``
    fills the output fields (and optionally rebinds the engine's page
    buffers) when someone needs them."""
    __slots__ = ("fut", "out", "toks", "lengths", "rem", "done", "K",
                 "greedy", "lanes", "srcs", "overlapped")

    def __init__(self, K, greedy, lanes, srcs, overlapped):
        self.fut = None
        self.out = None
        self.toks = None
        self.lengths = None
        self.rem = None
        self.done = None
        self.K = K
        self.greedy = greedy
        self.lanes = lanes
        self.srcs = srcs
        self.overlapped = overlapped


# every live engine, for the tests' refcount-invariant leak guard
# (tests/conftest.py checks each one after every test)
_LIVE_ENGINES: "weakref.WeakSet[ServingEngine]" = weakref.WeakSet()


class ServingEngine:
    """Continuous-batching decode engine over the paged KV cache.

    params: the (embed, block, head) pytrees `build_functional_llama` /
    `functional_params_from_layer` produce.  One jitted decode executable
    covers the whole run; prefill executables are cached per prompt-length
    bucket (per chunk size once `prefill_chunk` is set).

    `prefix_cache=True` (default) turns on automatic prefix caching:
    retired requests park their KV pages in a block-hash index, and later
    prompts sharing a page-aligned prefix attach those pages read-only and
    prefill only the suffix.  `prefill_chunk=N` bounds any single prefill
    dispatch to N tokens, interleaving long-prompt prefill with decode
    horizons (chunked prefill).  `speculative=K` turns on lossless
    self-speculative decoding: a host-side n-gram index over each
    request's prompt + emitted tokens drafts up to K continuation tokens
    (prompt-lookup — no draft model), one `verify_step` dispatch scores
    all K+1 positions, and the engine accepts the longest draft prefix
    whose argmax matches, emitting up to K+1 tokens per forward pass.
    All three knobs preserve greedy outputs bit-exactly vs the plain
    engine.  `overlap=True` double-buffers the host loop: step N+1 is
    scheduled and dispatched while step N's decode is still in flight,
    with the sampled-token/length/budget/done state carried ON DEVICE
    between dispatches and emitted tokens drained one batched fetch
    behind (bounded-lag retirement; `quiesce()` forces an exact
    boundary) — greedy outputs stay bit-exact vs `overlap=False` across
    the whole feature matrix.  `telemetry=True` (or a configured
    `observability.Telemetry`) records request-lifecycle traces, latency
    histograms, and the crash flight recorder — also without touching
    outputs.

    `kv_dtype="int8"|"fp8"` stores KV pages quantized with per-(page,
    head, token-row) absmax scales held in the pool (~4x more pages per
    byte at int8 — PagePool capacity is the admission bottleneck, so this
    is a direct concurrent-user win); `quantize=8` snaps the serving
    weights onto the per-channel int8 grid (serving/quant.py).  Both keep
    the engine deterministic and bit-exact against ITSELF across every
    feature above; parity vs the f32 engine is exact-match-rate gated
    (`serving.quant.parity_report`, `bench.py --trace quant`), not
    bit-equality — quantization is lossy by definition."""

    def __init__(self, params, config, num_slots: int = 4,
                 page_size: int = 16, num_pages: int | None = None,
                 max_pages_per_seq: int | None = None, dtype=None,
                 attention_impl: str = "auto", interpret: bool = False,
                 prompt_bucket: int = 32, decode_horizon: int = 8,
                 seed: int = 0, max_queue: int | None = None,
                 prefix_cache: bool = True, prefill_chunk: int | None = None,
                 speculative: int | None = None, spec_max_ngram: int = 3,
                 overlap: bool = False,
                 telemetry: "Telemetry | bool | None" = None,
                 name: str = "engine", kv_dtype: str | None = None,
                 quantize=None, mesh=None, mp_axis: str = "mp",
                 quantized_allreduce: bool = False):
        import jax
        import jax.numpy as jnp
        from ..models.llama import (build_llama_paged_decode,
                                    make_paged_decode_horizon,
                                    _sample_per_request)
        self._jax, self._jnp = jax, jnp
        # quantized serving plane (ROADMAP item 2): kv_dtype stores KV
        # pages int8/fp8 with per-(page, head, row) absmax scales held in
        # the pool's device arrays; quantize=<bits|True|"int8"> snaps the
        # serving weights onto the per-channel int grid at construction
        # (serving/quant.py).  Both knobs keep the engine deterministic
        # and self-bit-exact across the whole feature matrix — parity vs
        # the f32 engine is gated by serving.quant.parity_report instead
        # of bit-equality (quantization is lossy by definition).
        self.kv_dtype = None if kv_dtype is None else str(kv_dtype)
        # tensor-parallel serving (ROADMAP item 1): mesh=<Mesh binding
        # mp_axis> shards Q/KV heads, KV pages, and the MLP weight columns/
        # rows over mp — the whole horizon runs under shard_map with ONE
        # AllReduce per transformer layer (f32 psum, or the EQuARX int8
        # grid with quantized_allreduce=True; distributed/quant_collectives).
        # The dispatch/drain loop below is mesh-oblivious: every scalar the
        # host touches is replicated.
        self.mesh = mesh
        self.mp_axis = str(mp_axis)
        self.tp = 1 if mesh is None else int(mesh.shape[mp_axis])
        self.quantized_allreduce = bool(quantized_allreduce and self.tp > 1)
        if quantize:
            bits = 8 if quantize is True or quantize == "int8" \
                else int(quantize)
            from ..serving.quant import quantize_params
            params = quantize_params(params, bits=bits)
            self.quantize_bits = bits
        else:
            self.quantize_bits = None
        # replica identity: rides the serve.crash / serve.wedge fault-point
        # ctx so a fleet drill can target one replica (match={"engine": ...})
        self.name = str(name)
        # per-model-fn compile-cache miss counters (analysis.sanitize
        # instrumentation; stats()["jit_cache_misses"]) + the underlying
        # jitted fns for jit_variants() accounting
        self.jit_cache_misses: dict[str, int] = {}
        self._jit_fns: dict[str, list] = {}
        self.config = config
        self.params = params
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        cap_pages = math.ceil(config.max_position_embeddings / page_size)
        self.max_pages_per_seq = int(max_pages_per_seq or cap_pages)
        if num_pages is None:
            num_pages = self.num_slots * self.max_pages_per_seq
        self.pool = PagePool(num_pages, page_size)
        self.cache = PrefixCache(self.pool, page_size) if prefix_cache \
            else None
        self.prefill_chunk = None if prefill_chunk is None \
            else max(1, int(prefill_chunk))
        self.prompt_bucket = int(prompt_bucket)
        self.decode_horizon = max(1, int(decode_horizon))
        # speculative=K: lossless self-speculative decoding — n-gram drafts
        # verified K+1 positions at a time (greedy slots only; 0/None off)
        self.speculative = 0 if not speculative else int(speculative)
        self.spec_max_ngram = max(1, int(spec_max_ngram))
        # overlap=True: double-buffered async host loop (pipeline depth 1).
        # Buffer donation pins a dispatch to SYNCHRONOUS execution on the
        # XLA CPU backend (the PERF.md §14 "dispatch blocks" caveat,
        # root-caused) — but dropping donation would copy the whole page
        # pool every step.  Overlap mode therefore issues its decode
        # dispatches from a ONE-WORKER thread: donation (and the in-place
        # page update) is kept on every backend, the worker chains each
        # dispatch on the previous one's future, and the main thread only
        # blocks at the drain — true async on CPU, a no-op wrapper on a
        # backend whose dispatch is already async.
        self.overlap = bool(overlap)
        self._inflight: _Inflight | None = None
        self._executor = None
        if self.overlap:
            from concurrent.futures import ThreadPoolExecutor
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"{self.name}-dispatch")
            weakref.finalize(self, self._executor.shutdown, wait=False)
        # telemetry=True -> default Telemetry(); None/False -> OFF, and off
        # is a no-op fast path: every hook site below is one `is not None`
        # flag check, zero per-token Python work (observability/telemetry.py)
        self.telemetry: Telemetry | None = \
            Telemetry() if telemetry is True else (telemetry or None)
        # ONE clock domain: request timestamps (submit/admit/first-token/
        # retire/deadlines) share the telemetry clock when one is attached,
        # so an injected fake clock drives EVERY timestamp deterministically
        # (default Telemetry clock is time.perf_counter — no behavior change)
        self._clock = self.telemetry.clock if self.telemetry is not None \
            else time.perf_counter

        init_pages, prefill, prefill_chunk_fn, decode_step, verify_step = \
            build_llama_paged_decode(
                config, page_size=page_size, num_pages=num_pages, dtype=dtype,
                attention_impl=attention_impl, interpret=interpret,
                kv_dtype=self.kv_dtype, mesh=mesh, mp_axis=self.mp_axis,
                quantized_allreduce=self.quantized_allreduce)
        cache = init_pages()
        # each side is a raw [L, Hkv, NP+1, ps, D] array (f32/bf16) or a
        # {"q": data, "s": scales} dict (kv_dtype set); the engine treats
        # them as opaque pytrees everywhere except snapshot/restore
        self._pages_k, self._pages_v = cache["k"], cache["v"]
        if self.tp > 1:
            # commit params + pages onto the mesh with the same specs the
            # shard_map region expects, so every jitted fn compiles ONE
            # variant against stably-placed operands (no silent resharding,
            # no per-call device_put of the weights)
            from jax.sharding import NamedSharding, PartitionSpec
            from ..models.llama import (llama_paged_page_spec,
                                        llama_paged_param_specs)
            self.params = params = jax.tree_util.tree_map(
                lambda s, x: jax.device_put(x, NamedSharding(mesh, s)),
                llama_paged_param_specs(self.mp_axis), params,
                is_leaf=lambda s: isinstance(s, PartitionSpec))
            pg = NamedSharding(mesh, llama_paged_page_spec(self.mp_axis))
            place = lambda a: jax.device_put(a, pg)
            self._pages_k = jax.tree_util.tree_map(place, self._pages_k)
            self._pages_v = jax.tree_util.tree_map(place, self._pages_v)
        self._kv_compute_dtype = jnp.dtype(dtype) if dtype is not None \
            else jnp.float32
        self._page_bytes = None        # lazy page_bytes cache

        # decode HORIZON: K decode+sample steps fused into one fori_loop
        # dispatch (admission/retirement happen between horizons).  The
        # per-token python loop costs ~20 ms of dispatch round-trip on the
        # remote TPU transport (PERF.md §:llama_generate_fused) — K
        # amortizes it K-fold, which is what lets continuous batching beat
        # the single-dispatch static fused baseline.  The loop body lives
        # with the model math (models/llama.make_paged_decode_horizon);
        # it returns the sampled-token/length/budget/done carry as DEVICE
        # values so the overlapped engine feeds dispatch N+1 straight from
        # dispatch N's outputs — the synchronous engine passes host values
        # and done0=False, and the math is bit-identical either way.
        _horizon = make_paged_decode_horizon(decode_step,
                                             sample_fn=_sample_per_request)

        # prefill + first-token sample fused into ONE dispatch per admission
        # (a separate sample call would double the per-admission round-trips
        # on the remote TPU transport)
        def _prefill_sample(params, ids, true_len, page_row, pk, pv, key,
                            temp, top_p, *, greedy):  # graftlint: jit
            logits, pk, pv = prefill(params, ids, true_len, page_row, pk, pv)
            if greedy:
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                tok = _sample_per_request(logits[None], key, temp[None],
                                          top_p[None])[0]
            return tok, pk, pv

        # single-logits sampler for the final chunk of a chunked / suffix
        # prefill (the chunk executable itself is sampling-agnostic so one
        # executable serves every request)
        def _sample_logits(logits, key, temp, top_p, *, greedy):  # graftlint: jit
            if greedy:
                return jnp.argmax(logits).astype(jnp.int32)
            return _sample_per_request(logits[None], key, temp[None],
                                       top_p[None])[0]

        # copy-on-write page copy (src/dst are traced scalars: ONE
        # executable covers every copy).  tree_map keeps it generic over
        # the page-store layout: a raw array copies its page rows, a
        # quantized {"q","s"} store copies data AND scales — the page axis
        # is axis 2 of every leaf by construction.
        def _copy_page(pk, pv, src, dst):             # graftlint: jit
            def cp(a):
                return a.at[:, :, dst].set(a[:, :, src])
            return (jax.tree_util.tree_map(cp, pk),
                    jax.tree_util.tree_map(cp, pv))

        self._horizon_fn = _horizon
        self._horizon_jit = {}         # (K, greedy) -> jitted horizon
        self._prefill_fn = _prefill_sample
        self._prefill_jit = {}         # (T_bucket, greedy) -> jitted prefill
        # one wrapper: jax.jit already caches per (C_pad, P_slice) shape,
        # and the chunk fn has no Python-level static knobs to key on
        self._chunk_jit = self._jit("prefill_chunk", prefill_chunk_fn,
                                    donate_argnums=(5, 6))
        self._sample_fn = _sample_logits
        self._sample_jit = None        # lazily jitted nucleus sampler
        self._copy_jit = self._jit("page_copy", _copy_page,
                                   donate_argnums=(0, 1))
        # one wrapper: drafts pad to the STATIC K+1 query width, so the
        # verify executable compiles once per engine K (jax.jit caches by
        # shape) even when slots draft fewer tokens or none at all
        self._verify_jit = self._jit("verify_step", verify_step,
                                     donate_argnums=(4, 5))

        # host-side slot state
        S, P = self.num_slots, self.max_pages_per_seq
        self._slots: list[_Slot | None] = [None] * S
        self._page_tables = np.zeros((S, P), np.int32)
        self._lengths = np.zeros((S,), np.int32)
        self._temps = np.zeros((S,), np.float32)
        self._top_ps = np.ones((S,), np.float32)
        self._queue: deque[Request] = deque()
        self._finished: dict[int, Request] = {}
        self._next_rid = 0
        self._key = jax.random.PRNGKey(seed)
        self.max_queue = None if max_queue is None else int(max_queue)
        self._admit_seq = 0
        self._pressure = False         # this-step injected pool pressure
        self._step_seq = 0             # step() invocations (chunk pacing)
        self.steps_run = 0
        self.tokens_generated = 0
        self.preemptions = 0           # victim evictions (self-healing)
        self.timeouts = 0              # deadline retirements
        self.rejections = 0            # AdmissionRejected count
        self.cache_hits = 0            # admissions that attached a prefix
        self.cache_hit_tokens = 0      # prefill tokens skipped via the cache
        self.prefill_tokens = 0        # prefill tokens actually executed
        self.cache_evictions = 0       # cached pages evicted under pressure
        self.cow_copies = 0            # copy-on-write page copies
        self.verify_steps = 0          # speculative verify dispatches
        self.draft_tokens_proposed = 0  # draft tokens sent to verify
        self.draft_tokens_accepted = 0  # ... whose argmax matched
        self.overlap_steps = 0         # dispatches issued double-buffered
                                       #   (a previous step still in flight)
        self.fused_sample_steps = 0    # steady-state dispatches that emitted
                                       #   TOKENS on-device (fused greedy
                                       #   argmax / in-horizon sampling) —
                                       #   steps_run minus this = dispatches
                                       #   that returned logits for host-
                                       #   side sampling (sampled verify
                                       #   lanes)
        self.quiesces = 0              # pipeline drains forced by a
                                       #   host-exactness point (snapshot/
                                       #   cancel/deadline/ladder/verify)
        self.kv_exports = 0            # export_kv packets produced
        self.kv_imports = 0            # import_kv packets spliced in
        self.kv_pages_exported = 0     # pages shipped in those packets
        self.kv_pages_imported = 0
        _LIVE_ENGINES.add(self)

    # -- submission --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32, temperature: float = 0.0,
               top_p: float = 1.0, eos_token_id: int | None = None,
               timeout: float | None = None, on_token=None,
               trace_id: int | None = None) -> int:
        """Queue one request.  Raises `PoolCapacityError` for requests that
        can NEVER fit the pool geometry, `AdmissionRejected` when the bounded
        queue is full (backpressure), plain ValueError for malformed input.
        `timeout` (seconds from now) retires the request — wherever it is —
        once overdue, with `Request.timed_out` set.  `on_token` is the
        streaming hook: called as ``on_token(tok)`` for every emitted token
        in emission order, at the step's host-sync boundary (or the overlap
        drain — bounded lag, same order); `Request.stream()` is the
        pull-style equivalent.  `trace_id` (optional) is the fleet-wide
        stitching id the frontend/router minted for this end-to-end
        request (observability.distributed)."""
        now = self._clock()
        return self._enqueue(
            prompt, [], max_new_tokens, temperature, top_p, eos_token_id,
            None if timeout is None else now + float(timeout), now,
            on_token=on_token, trace_id=trace_id)

    def adopt(self, prompt, generated=(), max_new_tokens: int = 32,
              temperature: float = 0.0, top_p: float = 1.0,
              eos_token_id: int | None = None,
              deadline: float | None = None,
              trace_id: int | None = None) -> int:
        """Adopt a request MID-FLIGHT: queue `prompt` with `generated`
        tokens already emitted elsewhere (a crashed replica, a snapshot),
        to be continued from exactly that point.  Admission takes the
        preemption-resume path — re-prefill of prompt + generated[:-1]
        with generated[-1] as the pending token — so greedy continuation
        is bit-exact vs the engine that emitted those tokens.  Same
        validation + backpressure as :meth:`submit`; `deadline` is an
        absolute engine-clock cutoff (the migrating router's clock domain
        must match — in-process fleets share one clock)."""
        generated = [int(t) for t in generated]
        if max_new_tokens >= 1 and len(generated) >= max_new_tokens:
            raise ValueError(
                f"adopt: {len(generated)} tokens already emitted >= "
                f"max_new_tokens={max_new_tokens} — the request is complete, "
                f"nothing to continue (report it finished instead)")
        if eos_token_id is not None and eos_token_id in generated:
            raise ValueError(
                "adopt: generated already contains eos_token_id — the "
                "request is complete, nothing to continue")
        return self._enqueue(prompt, generated, max_new_tokens, temperature,
                             top_p, eos_token_id, deadline, self._clock(),
                             trace_id=trace_id)

    def _enqueue(self, prompt, generated, max_new_tokens, temperature,
                 top_p, eos_token_id, deadline, now, on_token=None,
                 trace_id=None) -> int:
        """Shared admission-queue entry for submit (fresh request, relative
        timeout already resolved to an absolute deadline) and adopt
        (mid-flight resume): validation, capacity check, backpressure, and
        Request construction live HERE, once."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + int(max_new_tokens)
        if total > self.config.max_position_embeddings:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the model context "
                f"{self.config.max_position_embeddings}")
        # the cache holds total-1 tokens (the final sampled token is never
        # written); it must fit this request's page-table row
        need = math.ceil((total - 1) / self.page_size)
        if need > self.max_pages_per_seq:
            raise PoolCapacityError(
                f"request needs {need} pages > "
                f"max_pages_per_seq={self.max_pages_per_seq} "
                f"(prompt {len(prompt)} + max_new_tokens {max_new_tokens})")
        if need > self.pool.num_pages:
            raise PoolCapacityError(
                f"request needs {need} pages but the pool only has "
                f"{self.pool.num_pages} ({self.pool.num_free} free) — raise "
                f"num_pages")
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            self.rejections += 1
            if self.telemetry is not None:
                self.telemetry.rejected(len(self._queue), self.max_queue)
            raise AdmissionRejected(
                f"admission queue full ({len(self._queue)}/{self.max_queue} "
                f"waiting, {self.num_active} active) — backpressure, retry "
                f"later")
        rid = self._next_rid
        self._next_rid += 1
        req = Request(rid=rid, prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_p=float(top_p),
                      eos_token_id=eos_token_id, submit_time=now,
                      deadline=deadline, generated=list(generated),
                      on_token=on_token, _engine=weakref.ref(self),
                      trace_id=None if trace_id is None else int(trace_id))
        self._queue.append(req)
        if self.telemetry is not None:
            self.telemetry.submitted(req, queue_depth=len(self._queue))
        return rid

    def lookup(self, rid: int) -> Request | None:
        """The Request for `rid` wherever it lives (slot, queue, finished);
        None for an unknown rid.  The returned object is live — a router
        streams tokens by watching its `generated` list grow."""
        r = self._finished.get(rid)
        if r is not None:
            return r
        for slot in self._slots:
            if slot is not None and slot.req.rid == rid:
                return slot.req
        for r in self._queue:
            if r.rid == rid:
                return r
        if self._inflight is not None:
            # budget-predicted retirement detached from the slot table but
            # not yet drained — still live, still streamable
            for lane in self._inflight.lanes:
                if lane.retiring and lane.slot.req.rid == rid:
                    return lane.slot.req
        return None

    def cancel(self, rid: int) -> bool:
        """Drop a request wherever it lives, recording no result: a queued
        request leaves the queue, a running slot releases (its written KV
        parks in the prefix cache first — the blocks are valid and future
        admissions may hit them), a finished record is forgotten.  Routers
        use this to prune snapshot-restored requests they already resolved
        elsewhere, so a revived replica does not decode zombies.  Returns
        True when the rid was found."""
        # quiesce only when the rid is actually riding the pipeline (slot
        # or in-flight lane): the common router case — pruning an
        # already-finished or queued zombie — must not stall a healthy
        # in-flight dispatch.  The drain may retire the rid itself; the
        # finished-dict pop below still resolves it.
        if any(sl is not None and sl.req.rid == rid for sl in self._slots) \
                or (self._inflight is not None
                    and any(ln.slot.req.rid == rid
                            for ln in self._inflight.lanes)):
            self.quiesce()     # cancellation acts on exact host state
        live = False
        for s, slot in enumerate(self._slots):
            if slot is not None and slot.req.rid == rid:
                self._register_slot(s, with_partial=True)
                self._release_slot(s)
                live = True
                break
        if not live:
            for r in self._queue:
                if r.rid == rid:
                    self._queue.remove(r)
                    live = True
                    break
        if live and self.telemetry is not None:
            # terminate the trace record (same ghost fix the router tracer
            # got in the stitching PR: Tracer._live is unbounded, and a
            # frontend with many disconnects would grow it forever); the
            # cancelled request stays attributable — its record moves to
            # the completed ring with a terminal `retired(cancelled)`.
            # LIVE paths only: an already-finished rid's record terminated
            # at retirement, and re-recording would mint a ghost duplicate
            self.telemetry.cancelled(rid)
        return live or self._finished.pop(rid, None) is not None

    # -- internals ---------------------------------------------------------
    def _jit(self, name, fn, **jit_kw):
        """jax.jit + recompile instrumentation: every compile-cache miss of
        the returned callable lands in `self.jit_cache_misses[name]` and is
        reported to any active `analysis.sanitize()` scope (the recompile
        budget).  All engine executables route through here so steady-state
        variant counts are observable per model fn — and, with telemetry
        attached, every miss's wall cost lands in `engine.compile_s` + a
        flight `compile` event (compile accounting)."""
        jf = self._jax.jit(fn, **jit_kw)
        # bounded by the (name, bucket) grid the budget gate polices,
        # not per-request  # graftlint: disable=LEAK001
        self._jit_fns.setdefault(name, []).append(jf)
        return instrument(jf, name=name, counters=self.jit_cache_misses,
                          on_miss=self._on_compile)

    def _on_compile(self, name, n, dur_s):
        """sanitize-instrumentation miss hook (host-only; telemetry off is
        one None check)."""
        tel = self.telemetry
        if tel is not None:
            tel.compiled(name, n, dur_s)

    def _call_paged(self, fn, *args):
        """Call a page-donating executable (its last two outputs are the
        new K/V page buffers).  A sanitize() budget raise fires only AFTER
        the underlying call ran — its donated inputs are gone — so rebind
        the page buffers from the executed call's outputs before
        propagating: lengths were never advanced for the raising step and
        K/V above lengths is never attended (the rewind invariant), so the
        engine stays fully usable."""
        try:
            out = fn(*args)
        except RecompileBudgetError as e:
            if e.result is not None:
                self._pages_k, self._pages_v = e.result[-2], e.result[-1]
            if self.telemetry is not None:
                # the postmortem the recompile sanitizer never had: the
                # last N engine events leading up to the budget failure
                self.telemetry.fault_dump("recompile_budget",
                                          error=str(e)[:200])
            raise
        return out

    def jit_variants(self) -> dict:
        """{model fn name: number of compiled executables} — the bounded,
        documented variant counts PERF.md §12 records (None-valued entries
        mean the jax build exposes no cache introspection)."""
        out = {}
        for name, fns in self._jit_fns.items():
            sizes = [jit_cache_size(f) for f in fns]
            out[name] = None if any(s is None for s in sizes) else sum(sizes)
        return out

    def _split_key(self):
        self._key, sub = self._jax.random.split(self._key)
        return sub

    def _avail(self) -> int:
        """Free pages as THIS step sees them: zero while an injected
        `serve.pool_pressure` window is active (exhaustion drills)."""
        return 0 if self._pressure else self.pool.num_free

    def _evict(self, n: int) -> int:
        """Degradation-ladder rung between stall and preempt: reclaim up to
        n pages from the prefix cache (LRU leaf-first)."""
        if self.cache is None or n <= 0:
            return 0
        freed = self.cache.evict(n)
        self.cache_evictions += freed
        if self.telemetry is not None:
            # recorded even at freed == 0: walking this rung is what the
            # flight-recorder ladder drills assert (admit -> evict ->
            # preempt), whether or not the cache had anything to give back
            self.telemetry.evicted(requested=n, freed=freed)
        return freed

    def _register_pages(self, slot, valid: int, with_partial: bool):
        """Index a slot's written-so-far KV (first `valid` tokens) into the
        prefix cache — host-list hashing only, no device access."""
        if self.cache is None or valid <= 0:
            return
        seq = np.concatenate(
            [slot.req.prompt,
             np.asarray(slot.req.generated, np.int32)])[:valid]
        self.cache.register(seq, slot.pages, with_partial=with_partial)

    def _register_slot(self, s: int, with_partial: bool):
        """Index the slot's written-so-far KV into the prefix cache (full
        blocks always; the trailing partial block too on retire/preempt,
        since nothing will write into it anymore)."""
        self._register_pages(self._slots[s], int(self._lengths[s]),
                             with_partial)

    def _release_slot(self, s: int):
        slot = self._slots[s]
        self.pool.free(slot.pages)
        self._slots[s] = None
        self._page_tables[s] = 0
        self._lengths[s] = 0
        return slot

    def _finish(self, s: int):
        # retire INTO the cache: the pages this request wrote stay indexed
        # (refcount 1, cache-held) until LRU eviction needs them back
        self._register_slot(s, with_partial=True)
        slot = self._release_slot(s)
        slot.req.finish_time = self._clock()
        self._finished[slot.req.rid] = slot.req
        if self.telemetry is not None:
            self.telemetry.retired(slot.req)

    def _preempt(self, s: int):
        """Victim preemption: park the slot's written KV in the prefix
        cache, return its page references, and requeue the request at the
        queue head; re-admission re-prefills prompt + already-emitted
        tokens — and that re-prefill can hit the very blocks parked here,
        so a preemption usually costs one chunk of suffix prefill, not a
        full re-prefill.  Greedy decoding resumes step-exact either way."""
        self._register_slot(s, with_partial=True)
        slot = self._release_slot(s)
        slot.req.preemptions += 1
        self.preemptions += 1
        if self.telemetry is not None:
            # storm detection lives in the telemetry (N preemptions within
            # a step window auto-dumps the flight recorder once per storm)
            self.telemetry.preempted(slot.req, step=self._step_seq)
        self._queue.appendleft(slot.req)

    def _pick_victim(self) -> int:
        """Youngest / lowest-progress victim: fewest emitted tokens, ties
        broken toward the most recent admission (least invested work)."""
        return min((s for s, sl in enumerate(self._slots) if sl is not None),
                   key=lambda s: (len(self._slots[s].req.generated),
                                  -self._slots[s].admit_seq))

    def _retire_overdue(self):
        """Deadline enforcement: retire overdue requests wherever they live
        (running slot or admission queue), marking them timed_out.  An
        overdue request currently riding the in-flight dispatch forces a
        quiesce first — the deadline acts on the drained step (bounded
        lag), never on a half-visible one."""
        now = self._clock()
        if self._inflight is not None:
            live = [sl.req for sl in self._slots if sl is not None]
            live += [ln.slot.req for ln in self._inflight.lanes
                     if ln.retiring]
            if any(r.deadline is not None and now > r.deadline
                   for r in live):
                self.quiesce()
        for s, slot in enumerate(self._slots):
            if slot is not None and slot.req.deadline is not None \
                    and now > slot.req.deadline:
                slot.req.timed_out = True
                self.timeouts += 1
                self._finish(s)
        if any(r.deadline is not None and now > r.deadline
               for r in self._queue):
            keep: deque[Request] = deque()
            for req in self._queue:
                if req.deadline is not None and now > req.deadline:
                    req.timed_out = True
                    req.finish_time = now
                    self.timeouts += 1
                    self._finished[req.rid] = req
                    if self.telemetry is not None:
                        self.telemetry.retired(req)
                else:
                    keep.append(req)
            self._queue = keep

    def _emit_token(self, slot, tok: int) -> bool:     # graftlint: hot
        """Append one sampled token (a PYTHON int — callers fetch at the
        annotated batched sync/drain boundaries and `.tolist()` rows, so
        no per-token device round-trip happens here), fire the streaming
        callback, and return True when the request just finished
        (EOS/budget).  Finishing bookkeeping stays with the caller — the
        slot may be attached (sync path) or detached (overlap drain of a
        pre-retired lane)."""
        req = slot.req
        req.generated.append(tok)
        if slot.draft is not None:
            slot.draft.append(tok)
        if req.first_token_time == 0.0:
            req.first_token_time = self._clock()
            if self.telemetry is not None:
                # once per request, inside the first-token branch — the
                # per-token fast path stays telemetry-free
                self.telemetry.first_token(req)
        if req.on_token is not None:
            req.on_token(tok)
        self.tokens_generated += 1
        return (req.eos_token_id is not None and tok == req.eos_token_id) \
            or len(req.generated) >= req.max_new_tokens

    def _record_token(self, s: int, tok: int) -> bool:  # graftlint: hot
        """Append a sampled token (already a host int); returns True when
        the request finished (and retires it in place)."""
        slot = self._slots[s]
        done = self._emit_token(slot, tok)
        if done:
            self._finish(s)
        else:
            slot.pending = tok
        return done

    def _finish_detached(self, slot, valid: int):
        """Retire a slot already DETACHED from the slot table (a budget-
        predicted retirement handed its lane to a successor while its
        final tokens were still in flight): park the written KV in the
        prefix cache, return the page references, record the result."""
        self._register_pages(slot, valid, with_partial=True)
        self.pool.free(slot.pages)
        slot.req.finish_time = self._clock()
        self._finished[slot.req.rid] = slot.req
        if self.telemetry is not None:
            self.telemetry.retired(slot.req)

    def _cow(self, s: int, idx: int, src: int | None = None):
        """Copy-on-write: give slot s its own copy of the (shared) page at
        table index idx before anything writes into it.  `src` overrides
        the copy source (admission attaches a cached partial page without
        ever putting the shared id in the table)."""
        jnp = self._jnp
        self._join_dispatch()      # the copy chains on concrete pages
        slot = self._slots[s]
        dst = slot.pages[idx]
        if src is None:
            src = dst
            dst = self.pool.alloc(1)[0]
        self._pages_k, self._pages_v = self._call_paged(
            self._copy_jit, self._pages_k, self._pages_v,
            jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32))
        if slot.pages[idx] != dst:
            self.pool.free([slot.pages[idx]])
            slot.pages[idx] = dst
        self._page_tables[s, idx] = dst
        self.cow_copies += 1
        if self.telemetry is not None:
            self.telemetry.cow_copy(slot.req.rid, src=int(src), dst=int(dst))

    def _admit(self):                                 # graftlint: hot
        jnp = self._jnp
        while self._queue:
            free_slots = [i for i, sl in enumerate(self._slots) if sl is None]
            if not free_slots:
                return
            req = self._queue[0]
            # resume path (preempted request): the cache must hold prompt +
            # all emitted tokens except the last, which becomes the pending
            # token — exactly the state the victim was evicted in
            resuming = len(req.generated) > 0
            ctx = req.prompt if not resuming else np.concatenate(
                # host list -> np ids, no device sync  # graftlint: disable=SYNC001
                [req.prompt, np.asarray(req.generated[:-1], np.int32)])
            T = len(ctx)
            total_pages = max(1, math.ceil(T / self.page_size))
            # longest cached prefix: whole pages attach read-only; a cached
            # partial tail page attaches via copy-on-write
            shared, partial = ([], None)
            if self.cache is not None:
                shared, partial = self.cache.lookup(ctx)
            n_shared = len(shared)
            # pin the matched pages (take our references now) so the
            # eviction below can never free them out from under us
            pin = list(shared) + ([partial[0]] if partial is not None else [])
            if pin:
                self.pool.share(pin)
            need = total_pages - n_shared   # pages this request must OWN
            if need > self._avail():
                # ladder: evict unreferenced cached pages before giving up
                self._evict(need - self._avail())
            if need > self._avail():
                if pin:
                    self.pool.free(pin)
                return                 # wait for retirements to free pages
            try:
                own = self.pool.alloc(need)
            except BaseException as exc:
                if pin:                # injected pagepool.alloc fault —
                    self.pool.free(pin)  # roll back so no reference leaks
                if self.telemetry is not None \
                        and isinstance(exc, InjectedFault):
                    self.telemetry.fault_dump("injected_fault",
                                              point="pagepool.alloc",
                                              error=str(exc)[:200])
                raise
            self._queue.popleft()
            s = free_slots[0]
            pages = shared + own
            matched = n_shared * self.page_size
            slot = _Slot(req, pages, 0, admit_seq=self._admit_seq)
            slot.resuming = resuming
            self._admit_seq += 1
            self._slots[s] = slot
            if self.speculative and req.temperature <= 0.0:
                # n-gram index over prompt + EVERY emitted token (ctx drops
                # the pending one; a preemption victim's index rebuilds
                # here from its full history)
                slot.spec_k = self.speculative
                slot.draft = _NgramDraft(
                    req.prompt if not resuming else np.concatenate(
                        # host list -> np ids, no device sync  # graftlint: disable=SYNC001
                        [req.prompt, np.asarray(req.generated, np.int32)]),
                    max_n=self.spec_max_ngram)
            row = np.zeros((self.max_pages_per_seq,), np.int32)
            row[:len(pages)] = pages
            self._page_tables[s] = row
            if partial is not None:
                # copy-on-write: the suffix prefill writes into this page's
                # tail, and the cache (and possibly other requests) still
                # reference it — copy first, then drop the pinned reference
                src, m = partial
                self._cow(s, n_shared, src=src)
                self.pool.free([src])
                matched += m
            self._temps[s] = req.temperature
            self._top_ps[s] = req.top_p
            if matched:
                self.cache_hits += 1
                self.cache_hit_tokens += matched
                req.cached_prefix_tokens += matched
            self.prefill_tokens += T - matched
            # admission timestamp at the host boundary we already stand on
            # (no device sync): first admission only, so queue_time keeps
            # meaning "wait for a slot" across preemption re-admissions
            admit_now = self._clock()
            first_admit = req.admit_time == 0.0
            if first_admit:
                req.admit_time = admit_now
            tel = self.telemetry
            if tel is not None:
                tel.admitted(req, slot=s, t=admit_now, resuming=resuming,
                             first=first_admit, cached_tokens=matched,
                             prefill_tokens=T - matched)
            chunked = self.prefill_chunk is not None \
                and (T - matched) > self.prefill_chunk
            if matched == 0 and not chunked:
                # whole-prompt dense prefill + fused first sample — the
                # pre-cache fast path, kept byte-identical so cache-off
                # numerics never shift
                self._lengths[s] = T
                # bucketed prompt pad -> one prefill executable per bucket
                # (clamped to the rope-table length: the bucket round-up may
                # overshoot the model context even though the prompt fits)
                Tb = max(self.prompt_bucket,
                         math.ceil(T / self.prompt_bucket) * self.prompt_bucket)
                Tb = min(Tb, self.config.max_position_embeddings)
                ids = np.zeros((1, Tb), np.int32)
                ids[0, :T] = ctx
                greedy = req.temperature <= 0.0
                pf = self._prefill_jit.get((Tb, greedy))
                if pf is None:
                    fn = self._prefill_fn
                    pf = self._jit(
                        "prefill",
                        (lambda *a: fn(*a, greedy=True)) if greedy
                        else (lambda *a: fn(*a, greedy=False)),
                        donate_argnums=(4, 5))
                    # keyed by (T bucket, greedy): bounded by the
                    # bucket ladder  # graftlint: disable=LEAK001
                    self._prefill_jit[(Tb, greedy)] = pf
                self._join_dispatch()   # prefill chains on concrete pages
                if tel is not None:
                    t_pf0 = tel.clock()
                    ann = tel.bridge_begin("prefill_dense")
                try:
                    tok, self._pages_k, self._pages_v = self._call_paged(
                        pf,
                        self.params, jnp.asarray(ids),
                        jnp.asarray(T, jnp.int32),
                        jnp.asarray(row), self._pages_k, self._pages_v,
                        self._split_key(),
                        jnp.asarray(req.temperature, jnp.float32),
                        jnp.asarray(req.top_p, jnp.float32))
                except RecompileBudgetError as e:
                    # the prefill DID run (pages already rebound by
                    # _call_paged) — finish the admission bookkeeping with
                    # the sampled token the raise carries, so the slot is
                    # left exactly as the success path leaves it and a
                    # later run() continues bit-exactly
                    if e.result is None:
                        raise
                    self._finish_admission(s, e.result[0], ctx, pages,
                                           resuming)
                    raise
                finally:
                    if tel is not None:
                        tel.bridge_end(ann)
                if tel is not None:
                    # dispatch span recorded BEFORE the bookkeeping below
                    # samples the first token, so the request record keeps
                    # ladder order: admitted -> prefill_dense -> first_token
                    tel.prefill_dispatch(req.rid, pos=0, tokens=T,
                                         t0=t_pf0, kind="prefill_dense")
                self._finish_admission(s, tok, ctx, pages, resuming)
            else:
                # suffix / chunked prefill: only the un-cached tokens run,
                # at most prefill_chunk per engine step
                slot.ctx = ctx
                slot.prefill_pos = matched
                self._lengths[s] = matched
                self._prefill_advance(s)

    def _finish_admission(self, s, tok, ctx, pages,
                          resuming):                  # graftlint: hot
        """Post-dense-prefill bookkeeping, shared by the success path and
        the RecompileBudgetError recovery path of _admit (the executed
        call's outputs ride the exception)."""
        slot = self._slots[s]
        if self.cache is not None:
            self.cache.register(ctx, pages)
        if resuming:
            # the re-prefill rebuilt the cache; the last emitted token is
            # still the pending one (a python int) — discard the
            # redundant sample
            slot.pending = slot.req.generated[-1]
        elif self.overlap:
            # on-device token carry: the fused prefill+sample's first
            # token never round-trips — the next decode dispatch consumes
            # it directly and the drain records it (bounded lag).  The
            # per-admission host sync the synchronous path pays below is
            # structurally GONE here.
            slot.pending = None
            slot.pending_dev = tok
        else:
            # the ONE per-admission sync: the fused prefill+sample's
            # first token  # graftlint: disable=SYNC001
            self._record_token(s, int(np.asarray(tok)))

    def _prefill_advance(self, s: int):               # graftlint: hot
        """Run ONE prefill chunk for slot s (suffix prefill after a cache
        hit is the single- or few-chunk case).  On the final chunk: index
        the prompt's full blocks into the cache and sample the first
        token."""
        jnp = self._jnp
        self._join_dispatch()      # the chunk chains on concrete pages
        slot = self._slots[s]
        req = slot.req
        pos = slot.prefill_pos
        T = len(slot.ctx)
        c = T - pos
        if self.prefill_chunk is not None:
            c = min(c, self.prefill_chunk)
        # bucket the chunk pad (a short suffix must not pay a full-chunk
        # executable) and slice the page table to the pages this chunk can
        # actually see (4-page granularity) — attention cost in the chunk
        # executable is C_pad x table_width, so both knobs matter, and on
        # TPU the kernel grid is proportional to the table width
        Cb = max(self.prompt_bucket,
                 math.ceil(c / self.prompt_bucket) * self.prompt_bucket)
        if self.prefill_chunk is not None:
            Cb = min(Cb, max(self.prompt_bucket, self.prefill_chunk))
        Cb = min(Cb, self.config.max_position_embeddings)
        ctx_pages = math.ceil((pos + c) / self.page_size)
        Pb = min(self.max_pages_per_seq, math.ceil(ctx_pages / 4) * 4)
        ids = np.zeros((1, Cb), np.int32)
        ids[0, :c] = slot.ctx[pos:pos + c]
        tel = self.telemetry
        if tel is not None:
            t_ck0 = tel.clock()
            ann = tel.bridge_begin("prefill_chunk")
        try:
            logits, tok_g, self._pages_k, self._pages_v = self._call_paged(
                self._chunk_jit,
                self.params, jnp.asarray(ids), jnp.asarray(pos, jnp.int32),
                jnp.asarray(c, jnp.int32),
                # .copy(): the row slice is a VIEW of the mutable host
                # table — an async in-flight chunk must not see later
                # host-side table growth (CPU jnp.asarray can alias)
                jnp.asarray(self._page_tables[s, :Pb].copy()),
                self._pages_k, self._pages_v)
        finally:
            if tel is not None:
                tel.bridge_end(ann)
        if tel is not None:
            tel.prefill_dispatch(req.rid, pos=pos, tokens=c, t0=t_ck0)
        slot.chunk_step = self._step_seq
        pos += c
        slot.prefill_pos = pos
        self._lengths[s] = pos
        if pos < T:
            return
        # prefill complete -> decoding
        slot.prefill_pos = None
        ctx, slot.ctx = slot.ctx, None
        if self.cache is not None:
            self.cache.register(ctx, slot.pages)
        if slot.resuming:
            # the re-prefill rebuilt the cache; the last emitted token is
            # still the pending one (a python int) — no fresh sample needed
            slot.pending = req.generated[-1]
        elif req.temperature <= 0.0:
            # fused greedy sampling: the chunk dispatch already emitted the
            # argmax token — no separate sample executable ever compiles
            # for the greedy final-chunk path
            if self.overlap:
                # on-device carry: no final-chunk host sync — the next
                # decode dispatch consumes the device scalar directly
                slot.pending = None
                slot.pending_dev = tok_g
            else:
                # the ONE final-chunk sync: the fused first token
                self._record_token(s, int(np.asarray(tok_g)))  # graftlint: disable=SYNC001
        else:
            try:
                tok = self._sampler(False)(
                    logits, self._split_key(),
                    jnp.asarray(req.temperature, jnp.float32),
                    jnp.asarray(req.top_p, jnp.float32))
            except RecompileBudgetError as e:
                # the sampler DID run — record the token it produced so
                # the completed-prefill transition above stays consistent
                # and a later run() decodes from the right first token
                if e.result is None:
                    raise
                self._record_token(s, int(np.asarray(e.result)))  # graftlint: disable=SYNC001
                raise
            if self.overlap:
                slot.pending = None
                slot.pending_dev = tok
            else:
                # the ONE final-chunk sync: the sampled first token
                self._record_token(s, int(np.asarray(tok)))  # graftlint: disable=SYNC001

    def _sampler(self, greedy: bool):
        """Jitted single-logits NUCLEUS sampler (the sampled final chunk
        of a chunked/suffix prefill and the sampled lanes of a speculative
        verify share it).  Greedy lanes never reach here — their argmax is
        FUSED into the chunk/verify/decode dispatch itself (tokens, not
        logits, leave the device), so the greedy sampler variant of the
        pre-unification engine no longer exists; `greedy` must be False."""
        assert not greedy, "greedy sampling is fused into the dispatch"
        sf = self._sample_jit
        if sf is None:
            fn = self._sample_fn
            sf = self._jit("sample", lambda *a: fn(*a, greedy=False))
            self._sample_jit = sf
        return sf

    def _remaining(self, s: int) -> int:
        slot = self._slots[s]
        n = slot.req.max_new_tokens - len(slot.req.generated)
        # an admission-deferred first token (overlap mode) is spoken for
        # but not yet in `generated` — it counts against the budget
        return n - 1 if slot.pending_dev is not None else n

    def _provision(self, steps):
        """Lazy page growth for up to `steps` decode steps ahead: every
        DECODING slot gets pages covering write positions < lengths +
        min(steps, remaining); mid-prefill slots are skipped (their pages
        were provisioned at admission).  `steps` is an int (uniform
        horizon) or a {slot: tokens} dict of per-slot needs (the verify
        path: 1 + draft length; slots absent from the dict are draftless
        ride-along lanes writing a single token).  When the pool runs
        short the prefix cache is evicted first (degradation ladder); a
        slot that still cannot be covered stalls this horizon.  A shared
        page about to receive a write is copied first (copy-on-write —
        belt and braces: admission already copies the only shareable
        written page).  Returns the list of runnable slot indices."""
        per_slot = steps if isinstance(steps, dict) else None
        run = []
        for s, slot in enumerate(self._slots):
            if slot is None or slot.prefill_pos is not None:
                continue
            want = per_slot.get(s, 1) if per_slot is not None else steps
            slot.stalled = False
            w0 = int(self._lengths[s]) // self.page_size
            if w0 < len(slot.pages) \
                    and self.pool.refcount(slot.pages[w0]) > 1:
                if self._avail() < 1:
                    self._evict(1)
                if self._avail() < 1:
                    slot.stalled = True
                    continue
                self._cow(s, w0)
            m = min(want, self._remaining(s))
            need = math.ceil((int(self._lengths[s]) + m) / self.page_size)
            grow = need - len(slot.pages)
            if grow > 0:
                if grow > self._avail():
                    self._evict(grow - self._avail())
                if grow > self._avail():
                    slot.stalled = True
                    continue
                pages = self.pool.alloc(grow)
                start = len(slot.pages)
                slot.pages.extend(pages)
                self._page_tables[s, start:start + grow] = pages
            run.append(s)
        return run

    # -- speculative decoding ----------------------------------------------
    def _propose_drafts(self) -> dict:
        """{slot -> draft tokens} for every decoding greedy slot whose
        n-gram index has a match this step.  Draft length is clamped to
        the slot's ADAPTIVE spec_k (shrunk while drafts keep missing,
        regrown on full acceptance) and to remaining-1 so an accepted run
        plus the bonus token can never overrun the request's budget — the
        page math then stays within the pages `submit` promised."""
        drafts = {}
        for s, slot in enumerate(self._slots):
            if slot is None or slot.prefill_pos is not None \
                    or slot.draft is None:
                continue
            k = min(slot.spec_k, self.speculative, self._remaining(s) - 1)
            if k <= 0:
                continue
            d = slot.draft.propose(k)
            if d:
                drafts[s] = d
        return drafts

    def _verify(self, run, drafts):                   # graftlint: hot
        """One speculative verify dispatch over the runnable slots: score
        pending + draft tokens at K+1 positions, accept the longest draft
        prefix whose argmax matches (lossless under greedy sampling), emit
        accepted tokens + the bonus token, and REWIND `lengths` past
        rejected positions — the stale K/V scattered for rejected drafts
        sits above the rewound length, is never attended (every attention
        path masks by lengths), and is overwritten by the next write at
        that position.  EOS/budget freezes mid-run exactly as in the
        decode horizon (`_record_token` stops the emit loop); sampled
        (temperature > 0) slots ride the same dispatch as single-token
        lanes drawn from the position-0 logits."""
        jnp = self._jnp
        Q = self.speculative + 1
        S = self.num_slots
        toks = np.zeros((S, Q), np.int32)
        n_q = np.zeros((S,), np.int32)
        for s in run:
            slot = self._slots[s]
            d = drafts.get(s, ())
            toks[s, 0] = slot.pending
            if d:
                toks[s, 1:1 + len(d)] = d
            n_q[s] = 1 + len(d)
        tel = self.telemetry
        if tel is not None:
            t_v0 = tel.clock()
            ann = tel.bridge_begin("verify_dispatch")
        try:
            logits0, gtoks, self._pages_k, self._pages_v = self._call_paged(
                self._verify_jit,
                self.params, jnp.asarray(toks), jnp.asarray(self._lengths),
                jnp.asarray(self._page_tables), self._pages_k,
                self._pages_v, jnp.asarray(n_q))
        finally:
            if tel is not None:
                tel.bridge_end(ann)
        t_v1 = tel.clock() if tel is not None else 0.0
        # the ONE per-verify-dispatch sync: every slot's K+1 argmaxes land
        # in one transfer (acceptance is host logic by design)
        gtoks = np.asarray(gtoks)  # graftlint: disable=SYNC001
        self.steps_run += 1
        self.verify_steps += 1
        if all(self._slots[s].req.temperature <= 0.0 for s in run):
            # every participating lane consumed the dispatch's own fused
            # argmax row — a token-emitting step; one sampled ride-along
            # lane makes it a logit-path dispatch instead
            self.fused_sample_steps += 1
        if tel is not None:
            t_v2 = tel.clock()
            tel.phase("verify_dispatch", t_v0, t_v1, slots=len(run))
            tel.phase("verify_sync", t_v1, t_v2)
            for s in run:
                tel.request_event(self._slots[s].req.rid, "verify_dispatch",
                                  drafted=len(drafts.get(s, ())))
        lens = self._lengths.tolist()    # host mirror -> python ints
        for s in run:
            slot = self._slots[s]
            req = slot.req
            d = list(drafts.get(s, ()))
            nd = len(d)
            old = lens[s]
            if req.temperature > 0.0:
                try:
                    tok = self._sampler(False)(
                        logits0[s], self._split_key(),
                        jnp.asarray(req.temperature, jnp.float32),
                        jnp.asarray(req.top_p, jnp.float32))
                except RecompileBudgetError as e:
                    # same recovery as the final-chunk sampler: the call
                    # ran and consumed a PRNG key — record its token so
                    # the resumed engine stays on the seeded key stream
                    # instead of re-sampling this position with a later key
                    if e.result is None:
                        raise
                    self._lengths[s] = old + 1
                    self._record_token(s, int(np.asarray(e.result)))  # graftlint: disable=SYNC001
                    raise
                # per sampled ride-along lane: one token fetch
                emitted = [int(np.asarray(tok))]  # graftlint: disable=SYNC001
                acc = 0
            else:
                g = gtoks[s].tolist()        # host row -> python ints
                acc = 0
                while acc < nd and g[acc] == d[acc]:
                    acc += 1
                emitted = d[:acc] + [g[acc]]
            if nd:
                if acc == nd:          # fully accepted: regrow toward K
                    slot.spec_k = min(self.speculative, slot.spec_k + 1)
                elif acc == 0:         # whiffed: back off (floor 1 — the
                    slot.spec_k = max(1, slot.spec_k // 2)  # lane is padded
                                       # to static K either way)
            n_emitted = 0
            for i, tok in enumerate(emitted, 1):
                # advance/rewind: cache now validly holds the pending token
                # plus i-1 accepted drafts past the old length
                self._lengths[s] = old + i
                n_emitted = i
                if self._record_token(s, tok):
                    break
            if nd:
                # credit only drafts that actually LANDED: an EOS/budget
                # freeze mid-run discards the tail of an accepted run, and
                # the reported acceptance rate must reflect useful tokens
                # (spec_k adaptation above still keys off model-level acc)
                used = min(acc, n_emitted)
                self.draft_tokens_proposed += nd
                self.draft_tokens_accepted += used
                req.draft_proposed += nd
                req.draft_accepted += used
        if tel is not None:
            tel.phase("verify_record", t_v2, tel.clock())

    def _horizon_exec(self, K: int, greedy: bool):
        fn = self._horizon_jit.get((K, greedy))
        if fn is None:
            fn = self._jit(
                "decode_step",
                lambda *a: self._horizon_fn(*a, K=K, greedy=greedy),
                donate_argnums=(4, 5))
            # keyed by (K, greedy): bounded by the horizon ladder
            # graftlint: disable=LEAK001
            self._horizon_jit[(K, greedy)] = fn
        return fn

    # -- double-buffered host loop (overlap=True; ROADMAP item 5) ----------
    @property
    def inflight_depth(self) -> int:
        """Decode dispatches in flight and not yet drained (0 or 1 — the
        pipeline is double-buffered, not arbitrarily deep)."""
        return 0 if self._inflight is None else 1

    def quiesce(self) -> bool:
        """Drain the pipeline to an EXACT host-visible step boundary:
        fetch and record any in-flight dispatch's tokens (retiring what
        finished) and flush any admission-deferred first tokens back to
        host ints.  After quiesce(), `Request.generated`, slot pendings,
        the length mirror, and the page accounting are precisely what a
        synchronous engine would hold — `snapshot()`, `cancel()`,
        deadline sweeps of in-flight work, speculative verify, and the
        degradation ladder all call this first.  Returns True when
        anything was actually in flight.  No-op (and free) on a
        synchronous engine."""
        rec, self._inflight = self._inflight, None
        flushed = False
        if rec is not None:
            self._drain(rec)
            self.quiesces += 1
            flushed = True
        for s, slot in enumerate(self._slots):
            if slot is not None and slot.pending_dev is not None:
                # materialized long ago (the dispatch that would consume
                # it never went out) — this fetch waits on nothing new
                tok0 = int(np.asarray(slot.pending_dev))
                slot.pending_dev = None
                if self._emit_token(slot, tok0):
                    self._finish(s)
                else:
                    slot.pending = tok0
                flushed = True
        return flushed

    def _flush_exhausted(self):
        """Record admission-deferred first tokens that already EXHAUST
        their request's budget (max_new_tokens == 1): such a lane must
        never enter a decode dispatch, so its token is fetched here —
        rare, and the fetch waits only on the admission prefill."""
        for s, slot in enumerate(self._slots):
            if slot is not None and slot.pending_dev is not None \
                    and slot.prefill_pos is None and self._remaining(s) <= 0:
                tok0 = int(np.asarray(slot.pending_dev))
                slot.pending_dev = None
                self._emit_token(slot, tok0)
                self._finish(s)      # budget-exhausted by construction

    def _detach_predicted(self):
        """Budget-predicted retirement: a lane whose IN-FLIGHT dispatch is
        guaranteed to finish its request — remaining budget <= the
        dispatched horizon; an EOS could only finish it sooner — hands
        its slot to the admission queue NOW instead of idling a full
        dispatch.  The predecessor's pages stay referenced by the lane
        record until the drain registers + frees them; the successor's
        prefill writes disjoint fresh pages, so the in-flight dispatch
        (which holds its own device copy of the page table) is
        untouched."""
        rec = self._inflight
        if rec is None:
            return
        for lane in rec.lanes:
            s, slot = lane.s, lane.slot
            if lane.retiring or self._slots[s] is not slot \
                    or slot.prefill_pos is not None:
                continue
            if self._remaining(s) <= rec.K:
                lane.retiring = True
                lane.base_len = int(self._lengths[s])
                self._slots[s] = None
                self._page_tables[s] = 0
                self._lengths[s] = 0

    def _dispatch_decode(self, run, K: int, greedy: bool):  # graftlint: hot
        """Issue one decode-horizon dispatch over the runnable lanes and
        return its `_Inflight` record WITHOUT fetching anything.  Lanes
        whose slot also rode the previous (possibly still in-flight)
        dispatch take their token/length/budget/done inputs from that
        dispatch's DEVICE outputs (the on-device carry); freshly admitted
        lanes merge in host values — and an admission-deferred first
        token joins as a device scalar, so it never round-trips either.

        Synchronous engines call the executable inline (donation makes
        that blocking on CPU — unchanged behavior).  Overlap engines
        submit the call to the one-worker thread, chaining on the
        previous dispatch's future INSIDE the worker, so the main thread
        returns immediately and the engine's page binding lives in the
        future until someone `_join_dispatch()`s or drains."""
        jnp = self._jnp
        S = self.num_slots
        prev = self._inflight
        active = np.zeros((S,), bool)
        active[run] = True
        toks = np.zeros((S,), np.int32)
        remaining = np.ones((S,), np.int32)
        eos_ids = np.full((S,), -1, np.int32)
        lanes = []
        carried = []
        deferred = []
        for s in run:
            slot = self._slots[s]
            remaining[s] = self._remaining(s)
            if slot.req.eos_token_id is not None:
                eos_ids[s] = slot.req.eos_token_id
            take_first = False
            if prev is not None and prev.srcs.get(s) is slot:
                carried.append(s)
            elif slot.pending_dev is not None:
                deferred.append((s, slot.pending_dev))
                take_first = True
            else:
                toks[s] = slot.pending
            lanes.append(_LaneRec(s, slot, take_first))
        cm = None
        if carried:
            cm = np.zeros((S,), bool)
            cm[carried] = True
        # .copy() the persistent host mirrors: the dispatch may execute
        # after the host has already mutated them (admissions, drains,
        # detaches), and jnp.asarray can ALIAS numpy memory on the CPU
        # backend.  The freshly built per-dispatch arrays need no copy.
        lengths_host = self._lengths.copy()
        tables = self._page_tables.copy()
        temps = self._temps.copy()
        top_ps = self._top_ps.copy()
        key = self._split_key()        # main thread: keeps the key stream
        fn = self._horizon_exec(K, greedy)

        def merge(prev_state):
            """Build the dispatch inputs; `prev_state` is (toks, lengths,
            rem, done) device arrays of the previous dispatch (None when
            nothing is carried).  Runs on the dispatching thread."""
            toks_in = jnp.asarray(toks)
            lengths_in = jnp.asarray(lengths_host)
            rem_in = jnp.asarray(remaining)
            done_in = jnp.zeros((S,), bool)
            if prev_state is not None and cm is not None:
                cmj = jnp.asarray(cm)
                toks_in = jnp.where(cmj, prev_state[0], toks_in)
                lengths_in = jnp.where(cmj, prev_state[1], lengths_in)
                rem_in = jnp.where(cmj, prev_state[2], rem_in)
                done_in = cmj & prev_state[3]
            for ds, dev in deferred:
                toks_in = toks_in.at[ds].set(dev)
            return toks_in, lengths_in, rem_in, done_in

        def call(pk, pv, toks_in, lengths_in, rem_in, done_in):
            return self._call_paged(
                fn, self.params, toks_in, lengths_in, jnp.asarray(tables),
                pk, pv, jnp.asarray(active), key, jnp.asarray(temps),
                jnp.asarray(top_ps), rem_in, jnp.asarray(eos_ids), done_in)

        tel = self.telemetry
        phase = "overlap_dispatch" if self.overlap else "decode_dispatch"
        if tel is not None:
            t_d0 = tel.clock()
            ann = tel.bridge_begin(phase)
        # carry sources are EXACTLY the dispatched lanes: only they got
        # real inputs merged in (a slot skipped by _provision this step
        # has default-filler rows in this dispatch — toks 0, remaining 1 —
        # and the horizon clobbers an inactive lane's token carry with the
        # eos filler), so a skipped lane must fall back to its host state,
        # which the previous drain left exact
        srcs = {lane.s: lane.slot for lane in lanes}
        rec = _Inflight(K, greedy, lanes, srcs, self.overlap)
        try:
            if not self.overlap:
                res = call(self._pages_k, self._pages_v, *merge(
                    None if prev is None
                    else (prev.toks, prev.lengths, prev.rem, prev.done)))
                rec.out, rec.toks, rec.lengths, rec.rem, rec.done = res[:5]
                self._pages_k, self._pages_v = res[-2], res[-1]
            elif prev is not None and prev.fut is not None:
                # chain INSIDE the worker: the previous dispatch's outputs
                # (pages + carry) flow worker-to-worker, never through the
                # main thread
                pfut = prev.fut

                def work_chained():
                    pres = pfut.result()
                    return call(pres[-2], pres[-1], *merge(
                        (pres[1], pres[2], pres[3], pres[4])))

                rec.fut = self._executor.submit(work_chained)
            else:
                # pipeline empty (or already joined by an admission): the
                # page binding and any carry state are concrete arrays
                pk0, pv0 = self._pages_k, self._pages_v
                pstate = None if prev is None \
                    else (prev.toks, prev.lengths, prev.rem, prev.done)
                rec.fut = self._executor.submit(
                    lambda: call(pk0, pv0, *merge(pstate)))
        finally:
            if tel is not None:
                tel.bridge_end(ann)
        self.steps_run += 1
        # horizon dispatches always emit tokens on-device (fused greedy
        # argmax or in-loop sampling) — logits never leave the device
        self.fused_sample_steps += 1
        if prev is not None:
            self.overlap_steps += 1
        if tel is not None:
            tel.phase(phase, t_d0, tel.clock(), slots=len(run), k=K)
            for s in run:
                tel.request_event(self._slots[s].req.rid, "decode_dispatch",
                                  k=K)
        return rec

    def _resolve(self, rec, rebind: bool):
        """Materialize an overlap dispatch's outputs from its future (and
        rebind the engine page buffers to them when `rec` is still the
        NEWEST dispatch — a superseded record's pages were already donated
        onward).  Re-raises the worker's exception (RecompileBudgetError:
        the worker's `_call_paged` already rebound the pages from the
        executed call, and the dispatch's tokens are discarded exactly as
        on the synchronous path)."""
        if rec.fut is None:
            return
        fut, rec.fut = rec.fut, None
        res = fut.result()
        rec.out, rec.toks, rec.lengths, rec.rem, rec.done = res[:5]
        if rebind:
            self._pages_k, self._pages_v = res[-2], res[-1]

    def _join_dispatch(self):
        """Block until the pending async dispatch's output binding is
        concrete (overlap mode), so a page-consuming executable — an
        admission prefill, a chunk, a COW copy — can chain on real
        arrays.  The drain of its TOKENS still happens later; joining is
        about the page buffers, not the step results."""
        rec = self._inflight
        if rec is None or rec.fut is None:
            return
        tel = self.telemetry
        t0 = tel.clock() if tel is not None else 0.0
        try:
            self._resolve(rec, rebind=True)
            if tel is not None:
                tel.join_wait(t0, tel.clock())
        except RecompileBudgetError:
            # the dispatch is discarded (its tokens were never recorded;
            # lengths never advanced — the rewind invariant); the worker
            # already rebound the page buffers, so the engine stays usable
            self._inflight = None
            raise

    def _drain(self, rec, rebind: bool = True):       # graftlint: hot
        """Fetch one dispatch's emitted tokens (ONE batched device sync)
        and replay the engine's freeze logic on the host: record tokens
        until each lane's EOS/budget stop — exactly mirroring the
        device-side freeze, so the host length mirror is reconstructed
        without fetching `lengths` at all — then retire what finished.
        Lanes whose slot was already retired by an earlier drain (an
        unpredicted EOS that rode one extra dispatch frozen) are
        skipped: their rows hold frozen `eos_ids` filler by
        construction.  `rebind=False` marks a record superseded by a
        newer dispatch (its page outputs were donated onward and must
        not re-bind)."""
        tel = self.telemetry
        t0 = tel.clock() if tel is not None else 0.0
        self._resolve(rec, rebind=rebind)
        # the ONE per-step sync: every lane's K tokens in one batched fetch
        out = np.asarray(rec.out)  # graftlint: disable=SYNC001
        t1 = tel.clock() if tel is not None else 0.0
        lens = self._lengths.tolist()     # host mirror -> python ints
        for lane in rec.lanes:
            s, slot = lane.s, lane.slot
            if not lane.retiring and self._slots[s] is not slot:
                continue           # retired by an earlier drain
            if slot.req.finish_time:
                continue
            base = lane.base_len if lane.retiring else lens[s]
            row = out[s].tolist()  # host ints, no per-token conversion
            done = False
            if lane.take_first and slot.pending_dev is not None:
                # the admission-deferred first token: materialized when
                # its dispatch ran — this fetch waits on nothing new
                tok0 = int(np.asarray(slot.pending_dev))  # graftlint: disable=SYNC001
                slot.pending_dev = None
                done = self._emit_token(slot, tok0)
            emitted = 0
            if not done:
                for tok in row:
                    emitted += 1
                    done = self._emit_token(slot, tok)
                    if done:
                        break
            if done:
                if lane.retiring:
                    self._finish_detached(slot, base + emitted)
                else:
                    self._lengths[s] = base + emitted
                    self._finish(s)
            else:
                # still live: the lane's last emitted token is the next
                # pending one; the device carry holds the same state
                self._lengths[s] = base + emitted
                slot.pending = row[emitted - 1]
        if tel is not None:
            pre = "overlap" if rec.overlapped else "decode"
            t2 = tel.clock()
            tel.phase(f"{pre}_sync", t0, t1)
            tel.phase(f"{pre}_record", t1, t2)

    # -- the serving loop --------------------------------------------------
    @property
    def num_active(self) -> int:
        return sum(1 for sl in self._slots if sl is not None)

    @property
    def page_bytes(self) -> int:
        """Bytes ONE pool page costs on device (K + V across all layers;
        per-page scales included when ``kv_dtype`` is set) — the unit the
        telemetry memory observatory multiplies page counts by, so
        capacity wins from quantized pages are visible in BYTES, not just
        page counts (`mem.pool_allocated_bytes` / `mem.pool_capacity_bytes`
        gauges, fleet snapshots).  Pure geometry — computed once and
        cached (the telemetry memory sampler reads it every step).  Under
        tensor parallelism this is the PER-CHIP cost: the KV-head axis is
        sharded over mp, so each chip holds 1/tp of every page."""
        pb = self._page_bytes
        if pb is None:
            from ..serving.quant import page_bytes
            pb = self._page_bytes = page_bytes(
                self.config, self.page_size, kv_dtype=self.kv_dtype,
                dtype=self._kv_compute_dtype) // self.tp
        return pb

    def step(self) -> bool:                           # graftlint: hot
        """One engine step: retire overdue requests, admit queued requests
        into free slots (attaching cached prefixes), advance each
        mid-prefill slot by one chunk, provision pages for the decode
        horizon, run the jitted K-step decode, record sampled tokens,
        retire finished requests into the prefix cache.  Returns True when
        any slot made progress.

        When nobody can progress — the former hard-deadlock RuntimeError —
        the engine walks the degradation ladder: evict unreferenced cached
        pages, then preempt a victim (pages parked in the cache, request
        requeued for re-prefill); under a fully injected pool-pressure
        window it parks and reports no progress.

        With telemetry on, the step's host wall time lands in the
        ``engine.step_host_s`` histogram, a per-step summary lands in the
        flight recorder, and an active injected pool-pressure window
        auto-dumps the recorder (postmortem for fault drills)."""
        tel = self.telemetry
        if tel is None:
            return self._step_impl()
        t0 = tel.clock()
        pre_tok = self.tokens_generated
        progressed = self._step_impl()
        tel.step_done(self, t0, progressed,
                      self.tokens_generated - pre_tok)
        return progressed

    def _step_impl(self) -> bool:                     # graftlint: hot
        jnp = self._jnp
        tel = self.telemetry
        t_s0 = tel.sched_begin() if tel is not None else 0.0
        self._step_seq += 1
        # serve.wedge: the engine "hangs" — the step returns without doing
        # ANY work (no admissions, no dispatch), the deterministic stand-in
        # for a replica that stopped responding.  A fleet watchdog sees
        # consecutive no-progress steps and declares the replica wedged.
        if fault_point("serve.wedge", engine=self.name,
                       step=self._step_seq) is not None:
            if tel is not None:
                tel.flight.record("fault", point="serve.wedge",
                                  step=self._step_seq)
            return False
        self._pressure = fault_point("serve.pool_pressure",
                                     step=self.steps_run) is not None
        pre_tokens = self.tokens_generated
        pre_finished = len(self._finished)
        # overlap: hand budget-predicted retiring lanes to the admission
        # queue before admitting, so a retirement costs zero lane idleness
        self._detach_predicted()
        self._retire_overdue()
        pre_admit_seq = self._admit_seq
        self._admit()
        if self.overlap:
            self._flush_exhausted()
        # serve.crash phase="sched": die mid-step AFTER admissions mutated
        # slot/pool state but BEFORE any token was produced this step — the
        # raising InjectedFault models the process dying; host state is
        # consistent (a step boundary for page accounting) but every
        # in-flight request is stranded until a fleet migrates it.
        fault_point("serve.crash", engine=self.name, step=self._step_seq,
                    phase="sched")
        if tel is not None:
            # host scheduling phase: deadline sweep + admissions — the
            # host-side cost the host-loop overlap refactor (ROADMAP item
            # 5) needs on the record.  Admission prefill dispatches run
            # inside this window but record their own spans; sched_done
            # subtracts them so the utilization buckets stay disjoint
            tel.sched_done(t_s0, tel.clock())
        # chunked prefill: each mid-prefill slot advances ONE chunk per
        # step, interleaved with the decode horizon below — a long prompt
        # never head-of-line blocks the running decodes or short arrivals.
        # A slot admitted THIS step already ran its first chunk inside
        # _admit (chunk_step guard), so the per-step prefill bound holds
        # on the admission step too.
        prefilled = False
        for s, slot in enumerate(self._slots):
            if slot is not None and slot.prefill_pos is not None \
                    and slot.chunk_step != self._step_seq:
                self._prefill_advance(s)
                prefilled = True
        if prefilled:
            self._admit()              # a 1-token request may have retired
        # speculative decoding: when any slot has a draft, ONE verify
        # dispatch scores K+1 positions per slot (slots without drafts ride
        # along as plain single-token lanes — mixed batches are the normal
        # case).  Draftless steps and pool-tight steps fall through to the
        # decode horizon below, so the degradation ladder is untouched.
        if self.speculative:
            drafts = self._propose_drafts()
            if drafts:
                # verify acceptance is HOST logic by design — the pipeline
                # drains first so every pending token is an exact host int
                # (overlap engines speculate on draftful steps at sync
                # pacing and double-buffer the draftless ones; drafts are
                # re-proposed on the drained state)
                if self._inflight is not None or any(
                        sl is not None and sl.pending_dev is not None
                        for sl in self._slots):
                    self.quiesce()
                    drafts = self._propose_drafts()
            if drafts:
                # per-slot need: 1 + draft length covers every K/V write
                # (padding lanes hit the trash page); draftless ride-along
                # lanes need a single token — no K+1 over-provisioning
                # that would evict cache / stall them under pool pressure
                run = self._provision(
                    {s: 1 + len(d) for s, d in drafts.items()})
                if run:
                    self._verify(run, drafts)
                    # serve.crash phase="record": die after this step's
                    # tokens were recorded but before anyone outside the
                    # engine observed them (mid-speculation intersection)
                    fault_point("serve.crash", engine=self.name,
                                step=self._step_seq, phase="record")
                    return True
        K = self.decode_horizon
        prev = self._inflight
        if prev is not None:
            # host lengths lag the in-flight dispatch by up to K tokens:
            # provision carried lanes for BOTH the in-flight writes and
            # this dispatch's (min(2K, remaining) is exact worst case);
            # fresh lanes provision the usual K
            want = {}
            for s, sl in enumerate(self._slots):
                if sl is not None and sl.prefill_pos is None:
                    want[s] = 2 * K if prev.srcs.get(s) is sl else K
            run = self._provision(want) if want else []
        else:
            run = self._provision(K)
        if not run and self._inflight is not None:
            # the pool cannot cover anyone while a step is in flight —
            # drain it (its retirements may free pages) and let the
            # degradation ladder act on exact state
            self.quiesce()
            run = self._provision(K)
        if not run and K > 1:
            # the pool cannot cover a full horizon for anyone — fall back to
            # single-step pacing so retirements can still free pages
            K = 1
            run = self._provision(1)
        # self-healing: cache eviction happens inside _provision/_admit;
        # when even that freed nothing usable, evict ONE victim per
        # no-progress step.  Freed pages go to the stalled SURVIVORS (no
        # re-admission here — the victim at the queue head would
        # immediately steal its own pages back and livelock).  One
        # eviction always suffices for a real deadlock: a stalled slot's
        # single-step growth need is <= 1 page and any victim frees >= 1
        # OWNED page (its suffix/COW page at minimum — cache-shared pages
        # may stay parked), so a survivor runs; when it doesn't (an
        # injected pool-pressure window hides every page), per-step
        # budgeting bounds the wasted re-prefills to one victim per
        # stalled step.
        # an admission THIS step ran its first prefill chunk inside _admit
        # (chunk_step guard) — that is progress, not a stall: without this,
        # a lone chunked-prefill admission with no decodable neighbor would
        # be preempted on its own admission step and thrash admit -> chunk
        # -> preempt until the prefix cache converged the re-prefills
        admitted = self._admit_seq != pre_admit_seq
        if not run and not prefilled and not admitted \
                and self.num_active > 0:
            self._preempt(self._pick_victim())
            K = 1
            run = self._provision(1)
        if not run:
            # pure-prefill step, pool-pressure window, or nothing to do
            # (any in-flight work was already drained above, so tokens /
            # retirements it produced still count as progress)
            return prefilled or admitted \
                or self.tokens_generated > pre_tokens \
                or len(self._finished) > pre_finished
        greedy = all(self._temps[s] <= 0.0 for s in run)
        try:
            rec = self._dispatch_decode(run, K, greedy)
            prev, self._inflight = self._inflight, rec
            if prev is not None:
                # drain step N-1's tokens WHILE step N runs: the fetch
                # waits only for N-1, and all host record/retire work
                # overlaps N
                self._drain(prev, rebind=False)
            if not self.overlap:
                # synchronous pacing: drain the dispatch we just issued
                self._inflight = None
                self._drain(rec)
        except RecompileBudgetError:
            # the raising dispatch's tokens are DISCARDED (lengths were
            # never advanced; K/V above lengths is never attended — the
            # rewind invariant), exactly as a synchronous engine discards
            # them; anything still drainable is drained so the pipeline
            # is empty when the error propagates
            try:
                self.quiesce()
            except RecompileBudgetError:
                pass           # the same failed dispatch, re-surfaced
            raise
        # serve.crash phase="record": die after this horizon's tokens were
        # recorded (and finished requests retired) but before any caller
        # observed them — a router that re-prefills from what it last
        # STREAMED must regenerate these tokens bit-identically (greedy)
        fault_point("serve.crash", engine=self.name, step=self._step_seq,
                    phase="record")
        return True

    def run(self, max_steps: int | None = None,
            max_stall_steps: int = 1000):
        """Drive until every submitted request finished; returns
        {rid: Request} (each with .generated / .output_ids filled).

        Consecutive no-progress steps (possible only while an injected
        pool-pressure window hides every page) are bounded by
        `max_stall_steps`; exceeding it raises `EngineStalledError` — the
        pool-sizing deadlock itself is resolved by cache eviction +
        preemption and can no longer raise."""
        steps = 0
        stalled = 0
        while self._queue or self.num_active or self._inflight is not None:
            progressed = self.step()
            stalled = 0 if progressed else stalled + 1
            if stalled >= max_stall_steps:
                if self.telemetry is not None:
                    # the flight recorder's reason for existing: dump the
                    # recent-event window BEFORE the engine dies
                    self.telemetry.fault_dump(
                        "engine_stalled", stalled_steps=stalled,
                        active=self.num_active, queued=len(self._queue),
                        free_pages=self.pool.num_free,
                        num_pages=self.pool.num_pages)
                raise EngineStalledError(
                    f"no engine progress for {stalled} consecutive steps "
                    f"({self.num_active} active, {len(self._queue)} queued, "
                    f"{self.pool.num_free} pages free of "
                    f"{self.pool.num_pages}) — a fault window that never "
                    f"clears?")
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return dict(self._finished)

    # -- snapshot / restore ------------------------------------------------
    # The engine's own durability (ROADMAP item 4): everything a process
    # restart would otherwise kill — in-flight Requests with emitted tokens,
    # the seeded RNG key stream, deadlines, slot table, page tables, PagePool
    # refcounts, prefix-cache index, adaptive spec state — serializes into a
    # versioned state dict and comes back bit-exactly.  Two modes:
    #
    #   * "full_kv": the referenced KV pages ride along raw — restore is a
    #     scatter back into the pool and decode CONTINUES without any
    #     re-prefill (fast restore; requires identical pool geometry);
    #   * "compact": token prefixes only — restore requeues every in-flight
    #     request through the preemption-resume path (re-prefill of prompt +
    #     emitted), so the snapshot is cheap and the restored pool may have
    #     a different size/geometry entirely.
    #
    # Greedy outputs are bit-exact across snapshot/restore in BOTH modes
    # (tests/test_fleet.py) — full_kv by construction, compact by the PR 2/3
    # preemption + re-prefill guarantee.  Snapshots are taken BETWEEN steps
    # (any step boundary is a consistent point for page accounting).

    SNAPSHOT_VERSION = 1

    def _req_state(self, r: Request) -> dict:
        eos = r.eos_token_id
        return {
            "rid": int(r.rid), "prompt": np.asarray(r.prompt).tolist(),
            "max_new_tokens": int(r.max_new_tokens),
            "temperature": float(r.temperature), "top_p": float(r.top_p),
            "eos_token_id": None if eos is None else int(eos),
            "deadline": None if r.deadline is None else float(r.deadline),
            "generated": [int(t) for t in r.generated],
            "submit_time": float(r.submit_time),
            "admit_time": float(r.admit_time),
            "first_token_time": float(r.first_token_time),
            "finish_time": float(r.finish_time),
            "timed_out": bool(r.timed_out),
            "preemptions": int(r.preemptions),
            "cached_prefix_tokens": int(r.cached_prefix_tokens),
            "draft_proposed": int(r.draft_proposed),
            "draft_accepted": int(r.draft_accepted),
            "trace_id": None if r.trace_id is None else int(r.trace_id),
        }

    @staticmethod
    def _req_from_state(d: dict) -> Request:
        return Request(
            rid=int(d["rid"]),
            prompt=np.asarray(d["prompt"], np.int32),
            max_new_tokens=int(d["max_new_tokens"]),
            temperature=float(d["temperature"]), top_p=float(d["top_p"]),
            eos_token_id=d["eos_token_id"], deadline=d["deadline"],
            generated=[int(t) for t in d["generated"]],
            submit_time=d["submit_time"], admit_time=d["admit_time"],
            first_token_time=d["first_token_time"],
            finish_time=d["finish_time"], timed_out=bool(d["timed_out"]),
            preemptions=int(d["preemptions"]),
            cached_prefix_tokens=int(d["cached_prefix_tokens"]),
            draft_proposed=int(d["draft_proposed"]),
            draft_accepted=int(d["draft_accepted"]),
            # .get: pre-ISSUE-12 snapshots carry no trace_id (version
            # unchanged — absent simply means "not stitched")
            trace_id=d.get("trace_id"))

    _COUNTER_ATTRS = ("steps_run", "tokens_generated", "preemptions",
                      "timeouts", "rejections", "cache_hits",
                      "cache_hit_tokens", "prefill_tokens",
                      "cache_evictions", "cow_copies", "verify_steps",
                      "draft_tokens_proposed", "draft_tokens_accepted",
                      "overlap_steps", "quiesces", "fused_sample_steps",
                      "kv_exports", "kv_imports", "kv_pages_exported",
                      "kv_pages_imported")

    def snapshot(self, mode: str = "full_kv",
                 include_finished: bool = True) -> dict:
        """Serialize the complete engine state at a step boundary.

        Returns a flat state dict ready for the crash-consistent
        ``distributed.checkpoint.save_state_dict`` writer (see
        ``serving.EngineSnapshotManager``): ``meta`` is one JSON string of
        host state, ``rng`` the engine PRNG key, and in ``full_kv`` mode
        ``kv_pages``/``kv_k``/``kv_v`` carry the referenced KV pages raw.
        ``include_finished`` keeps already-retired requests in the snapshot
        so a restored engine's ``run()`` still returns them."""
        if mode not in ("full_kv", "compact"):
            raise ValueError(f"unknown snapshot mode {mode!r}")
        # a snapshot is an EXACT state: drain the double-buffered pipeline
        # (in-flight tokens recorded, deferred first tokens flushed) so
        # the serialized pendings/lengths/pool are host-true
        self.quiesce()
        requests: dict[str, dict] = {}

        def _ref(r: Request) -> int:
            requests.setdefault(str(r.rid), self._req_state(r))
            return int(r.rid)

        slots = []
        for s, slot in enumerate(self._slots):
            if slot is None:
                slots.append(None)
                continue
            slots.append({
                "rid": _ref(slot.req),
                "pages": [int(p) for p in slot.pages],
                "pending": int(slot.pending),
                "admit_seq": int(slot.admit_seq),
                "prefill_pos": None if slot.prefill_pos is None
                else int(slot.prefill_pos),
                "ctx": None if slot.ctx is None
                else np.asarray(slot.ctx).tolist(),
                "resuming": bool(slot.resuming),
                "chunk_step": int(slot.chunk_step),
                "spec_k": int(slot.spec_k),
                "length": int(self._lengths[s]),
            })
        meta = {
            "version": self.SNAPSHOT_VERSION,
            "mode": mode,
            "geometry": {
                "num_slots": self.num_slots, "page_size": self.page_size,
                "num_pages": self.pool.num_pages,
                "max_pages_per_seq": self.max_pages_per_seq,
                "prefix_cache": self.cache is not None,
                # a full-KV snapshot's raw pages only scatter back into a
                # pool of the SAME kv_dtype (the stored bytes are that
                # dtype's codes + scales); any mismatch falls back to the
                # re-prefill path, which requantizes for the new store
                "kv_dtype": self.kv_dtype,
            },
            "requests": requests,
            "slots": slots,
            "queue": [_ref(r) for r in self._queue],
            "finished": [_ref(r) for r in self._finished.values()]
            if include_finished else [],
            "next_rid": int(self._next_rid),
            "admit_seq": int(self._admit_seq),
            "step_seq": int(self._step_seq),
            "counters": {k: int(getattr(self, k))
                         for k in self._COUNTER_ATTRS},
            "pool": {"free": [int(p) for p in self.pool._free],
                     "refs": [[int(p), int(c)]
                              for p, c in sorted(self.pool._refs.items())]},
        }
        state: dict = {"rng": np.asarray(self._key)}
        if mode == "full_kv":
            if self.cache is not None:
                c = self.cache
                meta["cache"] = {
                    "tick": int(c._tick), "insertions": int(c.insertions),
                    "evictions": int(c.evictions),
                    "full": [[e.key.hex(), e.parent.hex(), int(e.page),
                              int(e.tick)] for e in c._full.values()],
                    "partial": [[e.parent.hex(),
                                 np.frombuffer(e.tokens, np.int32).tolist(),
                                 int(e.page), int(e.tick)]
                                for d in c._partial.values()
                                for e in d.values()],
                }
            else:
                meta["cache"] = None
            ids = sorted(self.pool._refs)
            state["kv_pages"] = np.asarray(ids, np.int32)
            state.update(self._gather_pages(ids))
        state["meta"] = json.dumps(meta)
        return state

    def _gather_pages(self, ids) -> dict:
        """Pull pages `ids` to the host as named planes — the read half of
        the full-KV transfer primitive snapshot() and export_kv() share.
        The page axis is axis 2 of [L, Hkv, NP+1, ps, D] (the
        models/llama.gather_kv_pages contract); only pages holding a
        reference carry information (free pages are dead state, the trash
        page is garbage by contract).  Gather ON DEVICE first so the host
        transfer (both callers stand at a quiesced sync point) is
        proportional to live context, not pool capacity.  A quantized
        store ships data pages AND their per-row scales together — a
        splice that lost the scales would write back garbage magnitudes."""
        from ..models.llama import gather_kv_pages
        idx = self._jnp.asarray(np.asarray(ids, np.int32))
        gk = gather_kv_pages(self._pages_k, idx)
        gv = gather_kv_pages(self._pages_v, idx)
        if self.kv_dtype is not None:
            return {"kv_k_q": np.asarray(gk["q"]), "kv_k_s": np.asarray(gk["s"]),
                    "kv_v_q": np.asarray(gv["q"]), "kv_v_s": np.asarray(gv["s"])}
        return {"kv_k": np.asarray(gk), "kv_v": np.asarray(gv)}

    def _scatter_pages(self, ids, planes: dict):
        """Splice host planes (a `_gather_pages` result, same page order)
        into this engine's store at page ids `ids` — the write half of the
        transfer primitive `_restore_full` and `import_kv` share."""
        from ..models.llama import scatter_kv_pages
        idx = self._jnp.asarray(np.asarray(ids, np.int32))
        if self.kv_dtype is not None:
            self._pages_k = scatter_kv_pages(
                self._pages_k, idx,
                {"q": planes["kv_k_q"], "s": planes["kv_k_s"]})
            self._pages_v = scatter_kv_pages(
                self._pages_v, idx,
                {"q": planes["kv_v_q"], "s": planes["kv_v_s"]})
        else:
            self._pages_k = scatter_kv_pages(self._pages_k, idx,
                                             planes["kv_k"])
            self._pages_v = scatter_kv_pages(self._pages_v, idx,
                                             planes["kv_v"])

    # -- KV handoff (disaggregated prefill/decode) -------------------------
    KV_HANDOFF_VERSION = 1

    def handoff_ready(self, rid: int) -> bool:
        """True when `rid` rides a slot whose prefill is COMPLETE (dense,
        or every chunk executed) — the state a prefill-role replica hands
        to a decode replica.  First token is already banked (TTFT charged
        to the prefill engine); mid-chunked-prefill slots keep prefilling
        here.  Cheap host predicate — no quiesce, no device access."""
        for slot in self._slots:
            if slot is not None and slot.req.rid == rid:
                return (slot.prefill_pos is None and slot.ctx is None
                        and len(slot.req.generated) > 0)
        return False

    def export_kv(self, rids) -> dict:
        """Serialize the in-flight state of `rids` (slot-resident requests)
        plus exactly the KV pages their page tables reference, as one
        handoff packet for :meth:`import_kv` on another engine — the
        full-KV gather :meth:`snapshot` uses, scoped to a request subset.

        READ-ONLY on this engine: the caller decides when (whether) to
        `cancel` the source requests — cancelling parks their written KV
        in this engine's prefix cache, so a fallback re-prefill can still
        hit.  Raises KeyError for a rid not currently riding a slot
        (queued, finished, or unknown — nothing to hand off)."""
        # exact host state: drain the double-buffered pipeline first (the
        # drain itself may RETIRE a rid — the KeyError below reports it)
        self.quiesce()
        by_rid = {slot.req.rid: (s, slot)
                  for s, slot in enumerate(self._slots) if slot is not None}
        entries = []
        for rid in rids:
            if rid not in by_rid:
                raise KeyError(
                    f"export_kv: rid {rid} holds no slot (queued, finished "
                    "or unknown) — nothing to hand off")
            s, slot = by_rid[rid]
            entries.append({
                "req": self._req_state(slot.req),
                "pages": [int(p) for p in slot.pages],
                "pending": int(slot.pending),
                "prefill_pos": None if slot.prefill_pos is None
                else int(slot.prefill_pos),
                "ctx": None if slot.ctx is None
                else np.asarray(slot.ctx).tolist(),
                "resuming": bool(slot.resuming),
                "chunk_step": int(slot.chunk_step),
                "length": int(self._lengths[s]),
            })
        ids = sorted({p for e in entries for p in e["pages"]})
        planes = self._gather_pages(ids)
        packet = {
            "version": self.KV_HANDOFF_VERSION,
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype,
            "tp": self.tp,
            "kv_pages": [int(p) for p in ids],
            "planes": planes,
            "requests": entries,
            "bytes": int(sum(np.asarray(v).nbytes for v in planes.values())),
        }
        self.kv_exports += 1
        self.kv_pages_exported += len(ids)
        return packet

    def import_kv(self, packet: dict) -> dict:
        """Splice an :meth:`export_kv` packet into this RUNNING engine:
        allocate fresh pages, scatter the shipped planes into them, remap
        every request's page table onto the new ids, and seat the requests
        in free slots to continue decoding from exactly where the source
        engine stood — zero re-prefill, greedy bit-exact.

        Raises :class:`KVHandoffError` when the packet can NEVER splice
        here (page geometry / kv_dtype / tensor-parallel degree mismatch:
        head-sharded planes land rank-local only at EQUAL mp degree — the
        caller's fallback is re-prefill via `adopt`), and
        :class:`AdmissionRejected` for transient pressure (no free slot /
        no free pages even after the cache-eviction rung) — the ladder
        order of :meth:`_admit` is preserved.  Returns {source rid: rid
        minted here}."""
        if packet.get("version") != self.KV_HANDOFF_VERSION:
            raise KVHandoffError(
                f"kv handoff version {packet.get('version')!r} != "
                f"{self.KV_HANDOFF_VERSION}")
        if packet["page_size"] != self.page_size:
            raise KVHandoffError(
                f"page_size {packet['page_size']} != {self.page_size}: "
                "shipped pages cannot re-block without a device pass")
        if packet["kv_dtype"] != self.kv_dtype:
            raise KVHandoffError(
                f"kv_dtype {packet['kv_dtype']!r} != {self.kv_dtype!r}: "
                "stored codes/scales are the source dtype's — re-prefill "
                "requantizes for this store")
        if packet["tp"] != self.tp:
            raise KVHandoffError(
                f"mp degree {packet['tp']} != {self.tp}: head-sharded "
                "planes are rank-local only at equal mp degree — "
                "re-prefill (adopt) reshards for this submesh")
        entries = packet["requests"]
        if any(len(e["pages"]) > self.max_pages_per_seq for e in entries):
            raise KVHandoffError(
                "request page table exceeds this engine's "
                f"max_pages_per_seq={self.max_pages_per_seq}")
        # splice at an exact step boundary of THIS engine
        self.quiesce()
        free_slots = [i for i, sl in enumerate(self._slots) if sl is None]
        if len(entries) > len(free_slots):
            raise AdmissionRejected(
                f"import_kv: {len(entries)} requests > {len(free_slots)} "
                "free slots")
        old_ids = [int(p) for p in packet["kv_pages"]]
        n = len(old_ids)
        if n > self._avail():
            # ladder: evict unreferenced cached pages before giving up
            self._evict(n - self._avail())
        if n > self._avail():
            raise AdmissionRejected(
                f"import_kv: need {n} pages, {self._avail()} free after "
                "eviction")
        new_ids = self.pool.alloc(n)
        remap = dict(zip(old_ids, new_ids))
        self._scatter_pages(new_ids, packet["planes"])
        # extra references for pages shared by several shipped tables
        # (handed-off requests that shared a cached prefix on the source)
        nrefs: dict[int, int] = {}
        for e in entries:
            for p in e["pages"]:
                nrefs[p] = nrefs.get(p, 0) + 1
        extra = [remap[p] for p, c in nrefs.items() for _ in range(c - 1)]
        if extra:
            self.pool.share(extra)
        mapping: dict[int, int] = {}
        now = self._clock()
        for e, s in zip(entries, free_slots):
            d = dict(e["req"])
            src_rid = int(d["rid"])
            d["rid"] = self._next_rid
            self._next_rid += 1
            req = self._req_from_state(d)
            mapping[src_rid] = req.rid
            pages = [remap[p] for p in e["pages"]]
            slot = _Slot(req, pages, int(e["pending"]),
                         admit_seq=self._admit_seq)
            self._admit_seq += 1
            slot.prefill_pos = e["prefill_pos"]
            slot.ctx = None if e["ctx"] is None \
                else np.asarray(e["ctx"], np.int32)
            slot.resuming = bool(e["resuming"])
            slot.chunk_step = int(e["chunk_step"])
            if self.speculative and req.temperature <= 0.0:
                # drafting is THIS engine's capability (verify executables
                # compile per engine K): rebuild the pure-function n-gram
                # index from the shipped token stream
                slot.spec_k = self.speculative
                slot.draft = _NgramDraft(
                    np.concatenate([req.prompt,
                                    np.asarray(req.generated, np.int32)]),
                    max_n=self.spec_max_ngram)
            self._slots[s] = slot
            row = np.zeros((self.max_pages_per_seq,), np.int32)
            row[:len(pages)] = pages
            self._page_tables[s] = row
            self._lengths[s] = int(e["length"])
            self._temps[s] = req.temperature
            self._top_ps[s] = req.top_p
            if self.telemetry is not None:
                # stitched-trace continuity (the restore convention): the
                # handed-off request opens a track on THIS engine's tracer
                # whose first event carries handoff=True — the attribution
                # gap classifier turns the inter-engine gap into a
                # `kv_transfer` segment
                attrs = {"handoff": True}
                if req.trace_id is not None:
                    attrs["trace_id"] = req.trace_id
                self.telemetry.request_event(req.rid, "submitted", t=now,
                                             **attrs)
        self.kv_imports += 1
        self.kv_pages_imported += n
        return mapping

    def restore(self, state: dict) -> str:
        """Load a :meth:`snapshot` state dict into this FRESH engine
        (construct with the same params/config first; raises if this engine
        already ran work).  Returns the restore path taken:

          * ``"full_kv"`` — pool geometry matched a full-KV snapshot: KV
            pages scattered back, slots/page tables/cache rebuilt in place,
            decode continues with zero re-prefill;
          * ``"reprefill"`` — compact snapshot, OR a full-KV snapshot whose
            geometry no longer fits (e.g. restored into a smaller pool):
            every in-flight request requeues through the preemption-resume
            path and re-prefills prompt + emitted tokens, walking the
            normal admission ladder of THIS engine's pool.

        Greedy outputs are bit-exact vs the uninterrupted engine either
        way."""
        meta = state["meta"]
        if isinstance(meta, (bytes, np.ndarray)):
            meta = bytes(meta).decode()
        if isinstance(meta, str):
            meta = json.loads(meta)
        if meta.get("version") != self.SNAPSHOT_VERSION:
            raise ValueError(
                f"engine snapshot version {meta.get('version')!r} != "
                f"{self.SNAPSHOT_VERSION}")
        if self.num_active or self._queue or self._finished or self.steps_run:
            raise RuntimeError(
                "ServingEngine.restore: target engine already holds state — "
                "restore into a freshly constructed engine")
        jnp = self._jnp
        self._key = jnp.asarray(np.asarray(state["rng"]))
        reqs = {int(r): self._req_from_state(d)
                for r, d in meta["requests"].items()}
        for rid in meta["finished"]:
            self._finished[rid] = reqs[rid]
        self._next_rid = max(int(meta["next_rid"]), self._next_rid)
        for k, v in meta["counters"].items():
            setattr(self, k, int(v))
        self._admit_seq = int(meta["admit_seq"])
        g = meta["geometry"]
        fast = (meta["mode"] == "full_kv"
                and g["num_slots"] == self.num_slots
                and g["page_size"] == self.page_size
                and g["num_pages"] == self.pool.num_pages
                and g["max_pages_per_seq"] == self.max_pages_per_seq
                and bool(g["prefix_cache"]) == (self.cache is not None)
                # .get: pre-quant snapshots carry no kv_dtype (== f32/bf16
                # raw pages, the None default)
                and g.get("kv_dtype") == self.kv_dtype)
        if fast:
            self._restore_full(meta, state, reqs)
            applied = "full_kv"
        else:
            self._restore_reprefill(meta, reqs)
            applied = "reprefill"
        if self.telemetry is not None:
            # stitched-trace continuity: a restored in-flight request gets
            # a trace record (carrying its trace_id) on THIS engine's
            # tracer, so a failover revival appears as its own track in
            # the stitched Perfetto view.  Counters stay untouched — the
            # request was submitted elsewhere; this engine carries it on.
            now = self._clock()
            live = [sl.req for sl in self._slots if sl is not None]
            live.extend(self._queue)
            for r in live:
                attrs = {"restored": True}
                if r.trace_id is not None:
                    attrs["trace_id"] = r.trace_id
                self.telemetry.request_event(r.rid, "submitted", t=now,
                                             **attrs)
        return applied

    def _restore_full(self, meta, state, reqs):
        self._step_seq = int(meta["step_seq"])
        pool = self.pool
        pool._free = [int(p) for p in meta["pool"]["free"]]
        pool._refs = {int(p): int(c) for p, c in meta["pool"]["refs"]}
        ids = np.asarray(state["kv_pages"], np.int32)
        if len(ids):
            self._scatter_pages(ids, state)
        for s, sd in enumerate(meta["slots"]):
            if sd is None:
                continue
            req = reqs[sd["rid"]]
            slot = _Slot(req, [int(p) for p in sd["pages"]],
                         int(sd["pending"]), admit_seq=int(sd["admit_seq"]))
            slot.prefill_pos = sd["prefill_pos"]
            slot.ctx = None if sd["ctx"] is None \
                else np.asarray(sd["ctx"], np.int32)
            slot.resuming = bool(sd["resuming"])
            slot.chunk_step = int(sd["chunk_step"])
            slot.spec_k = int(sd["spec_k"])
            if self.speculative and req.temperature <= 0.0:
                # the n-gram index is a pure function of the token stream —
                # rebuild instead of serializing (identical by construction:
                # admission + per-token appends == one pass over the stream)
                slot.draft = _NgramDraft(
                    np.concatenate([req.prompt,
                                    np.asarray(req.generated, np.int32)]),
                    max_n=self.spec_max_ngram)
            self._slots[s] = slot
            row = np.zeros((self.max_pages_per_seq,), np.int32)
            row[:len(slot.pages)] = slot.pages
            self._page_tables[s] = row
            self._lengths[s] = int(sd["length"])
            self._temps[s] = req.temperature
            self._top_ps[s] = req.top_p
        for rid in meta["queue"]:
            self._queue.append(reqs[rid])
        if self.cache is not None and meta.get("cache"):
            c = self.cache
            cm = meta["cache"]
            c._tick = int(cm["tick"])
            c.insertions = int(cm["insertions"])
            c.evictions = int(cm["evictions"])
            for key_hex, parent_hex, page, tick in cm["full"]:
                e = _CacheEntry(bytes.fromhex(key_hex),
                                bytes.fromhex(parent_hex), int(page))
                e.tick = int(tick)
                c._full[e.key] = e
            for parent_hex, toks, page, tick in cm["partial"]:
                parent = bytes.fromhex(parent_hex)
                tb = np.asarray(toks, np.int32).tobytes()
                e = _CacheEntry(None, parent, int(page), tokens=tb)
                e.tick = int(tick)
                c._partial.setdefault(parent, {})[tb] = e
            for e in list(c._full.values()) + [
                    e for d in c._partial.values() for e in d.values()]:
                if e.parent in c._full:
                    c._full[e.parent].children += 1

    def _restore_reprefill(self, meta, reqs):
        """Compact-mode (or geometry-mismatch) restore: requeue every
        in-flight request through the preemption-resume machinery, slots
        first in admission order (they were running; they get slots back
        first), then the parked queue in its order.  The prefix cache
        starts empty — its pages' CONTENT did not ride a compact snapshot —
        and refills as re-prefills register blocks."""
        inflight = sorted((sd for sd in meta["slots"] if sd is not None),
                          key=lambda sd: sd["admit_seq"])
        for sd in inflight:
            self._queue.append(reqs[sd["rid"]])
        for rid in meta["queue"]:
            self._queue.append(reqs[rid])

    # -- accounting / invariants -------------------------------------------
    def stats(self) -> dict:
        """Engine observability: one dict of monotonically increasing
        counters (bench traces print it; dashboards diff it).
        `decode_steps` and `verify_steps` are DISJOINT dispatch counts
        (plain horizon vs speculative verify); their sum is the total
        number of engine dispatches (`steps_run`)."""
        prop = self.draft_tokens_proposed
        acc = self.draft_tokens_accepted
        return {
            "tokens_generated": self.tokens_generated,
            "decode_steps": self.steps_run - self.verify_steps,
            "verify_steps": self.verify_steps,
            # steady-state dispatches whose tokens were consumed from the
            # dispatch itself (fused greedy argmax / in-horizon sampling)
            # vs `steps_run` total: the remainder returned logits for
            # host-side sampling (sampled verify ride-along lanes)
            "fused_sample_steps": self.fused_sample_steps,
            "draft_tokens_proposed": prop,
            "draft_tokens_accepted": acc,
            "draft_accept_rate": round(acc / prop, 4) if prop else 0.0,
            "prefill_tokens_executed": self.prefill_tokens,
            "cached_prefix_tokens": self.cache_hit_tokens,
            "cache_hits": self.cache_hits,
            "cache_evictions": self.cache_evictions,
            "cow_copies": self.cow_copies,
            "preemptions": self.preemptions,
            "timeouts": self.timeouts,
            "rejections": self.rejections,
            # double-buffered host loop (overlap=True): dispatches that
            # went out while the previous step was still in flight, and
            # forced pipeline drains (exactness points)
            "overlap_steps": self.overlap_steps,
            "quiesces": self.quiesces,
            # disaggregated prefill/decode: export_kv/import_kv traffic
            # through this engine (pages = post-dedup shipped page count)
            "kv_exports": self.kv_exports,
            "kv_imports": self.kv_imports,
            "kv_pages_exported": self.kv_pages_exported,
            "kv_pages_imported": self.kv_pages_imported,
            # tensor-parallel serving: mesh degree over mp (1 = single
            # chip) and whether the per-layer AllReduce rides the EQuARX
            # int8 grid (distributed/quant_collectives)
            "tp_degree": self.tp,
            "quantized_allreduce": self.quantized_allreduce,
            # per-model-fn compile-cache misses (analysis.sanitize
            # instrumentation) — a warmed steady state must hold these
            # flat; bench --json artifacts embed them via engine_stats
            "jit_cache_misses": dict(self.jit_cache_misses),
        }

    def stats_snapshot(self):
        """Immutable flattened :class:`EngineStats` snapshot of `stats()`
        (nested dicts dotted).  Two snapshots diff exactly:
        ``later.delta(earlier)`` is the per-window activity — the
        registry-backed replacement for hand-diffing the stats() dict."""
        from ..observability.metrics import EngineStats
        return EngineStats.capture(self.stats(), clock=self._clock)

    def release_cache(self) -> int:
        """Drop every evictable cached page back to the free list (tests,
        shutdown, or a host that wants its HBM back); returns pages
        freed.  Pages attached to live requests are untouched."""
        if self.cache is None:
            return 0
        freed = self.cache.evict(self.pool.num_pages)
        self.cache_evictions += freed
        return freed

    def check_invariants(self):
        """Page-refcount accounting must exactly equal what the live page
        tables + prefix cache reference — called by the tests' leak guard
        after every test, and valid at ANY step boundary."""
        expect: dict[int, int] = {}
        for slot in self._slots:
            if slot is None:
                continue
            for p in slot.pages:
                expect[p] = expect.get(p, 0) + 1
        if self._inflight is not None:
            # budget-predicted retirements detached from the slot table
            # hold their pages through the lane record until drained
            for lane in self._inflight.lanes:
                if lane.retiring:
                    for p in lane.slot.pages:
                        expect[p] = expect.get(p, 0) + 1
        if self.cache is not None:
            for p in self.cache.pages():
                expect[p] = expect.get(p, 0) + 1
        assert expect == self.pool._refs, (
            f"page refcount drift: tables+cache say {expect}, "
            f"pool says {self.pool._refs}")
        assert self.pool.num_free + self.pool.num_allocated \
            == self.pool.num_pages, "free + allocated != pool size"
        free = self.pool._free
        assert len(set(free)) == len(free), "duplicate page on the free list"
        assert not (set(free) & set(self.pool._refs)), \
            "page simultaneously free and referenced"


def serve_requests(params, config, prompts, **kw):
    """One-shot convenience: submit every (prompt, request-kwargs) pair and
    run to completion.  `prompts` is a list of token arrays or
    (token_array, {request kwargs}) tuples; engine kwargs ride **kw."""
    req_kw_keys = ("max_new_tokens", "temperature", "top_p", "eos_token_id",
                   "timeout")
    default_req = {k: kw.pop(k) for k in req_kw_keys if k in kw}
    eng = ServingEngine(params, config, **kw)
    rids = []
    for p in prompts:
        if isinstance(p, tuple):
            p, rkw = p
            merged = dict(default_req)
            merged.update(rkw)
        else:
            merged = dict(default_req)
        rids.append(eng.submit(p, **merged))
    done = eng.run()
    return [done[r] for r in rids], eng
