"""Inference deployment API (reference: paddle/fluid/inference/api/
analysis_predictor.h:101 AnalysisPredictor, paddle_inference_api.h Config).

TPU-native design: the "inference program + optimization passes + executor"
stack collapses into the StableHLO artifact `paddle_tpu.jit.save` exports
(XLA is the optimizer + executor). `Predictor` is the serving-facing
wrapper: named input/output handles, copy-in/run/copy-out semantics, and an
AOT-compiled callable cached per input signature.
"""
from __future__ import annotations

import numpy as np

from .paged import (PagePool, PrefixCache, Request, ServingEngine,
                    serve_requests, PoolCapacityError, AdmissionRejected,
                    EngineStalledError, PageDoubleFreeError)

__all__ = ["Config", "Predictor", "create_predictor", "PredictorHandle",
           "PagePool", "PrefixCache", "Request", "ServingEngine",
           "serve_requests", "PoolCapacityError", "AdmissionRejected",
           "EngineStalledError", "PageDoubleFreeError"]


class Config:
    """reference paddle.inference.Config — model path + runtime knobs.
    Device/memory knobs are accepted for API parity; XLA owns both."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config("model_dir/inference") prefix, or
        # (prog_file, params_file) pair whose common prefix is the artifact
        self._prefix = None
        if prog_file is not None:
            p = str(prog_file)
            for suf in (".pdmodel.stablehlo", ".pdmodel", ".pdiparams"):
                if p.endswith(suf):
                    p = p[: -len(suf)]
                    break
            self._prefix = p
        self._enable_memory_optim = True
        self._device = "tpu"

    def set_prog_file(self, path):
        p = str(path)
        for suf in (".pdmodel.stablehlo", ".pdmodel", ".pdiparams"):
            if p.endswith(suf):
                p = p[: -len(suf)]
                break
        self._prefix = p

    def prog_file(self):
        return self._prefix + ".pdmodel"

    def enable_use_gpu(self, *a, **k):  # accepted for parity; device is TPU
        self._device = "gpu_requested(tpu)"

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def disable_glog_info(self):
        pass

    def switch_ir_optim(self, flag=True):
        pass  # XLA pass pipeline always on


class PredictorHandle:
    """Input/output tensor handle (reference ZeroCopyTensor)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else None


class Predictor:
    """reference AnalysisPredictor: named handles + Run().

    Wraps a `jit.load`-ed TranslatedLayer (StableHLO artifact). Input names
    come from the export metadata when present, else positional `x0, x1...`.
    """

    def __init__(self, config: Config):
        from ..jit.save_load import load as jit_load
        if config._prefix is None:
            raise ValueError("Config has no model path")
        self._layer = jit_load(config._prefix)
        meta = getattr(self._layer, "_meta", {}) or {}
        names = meta.get("input_names")
        if not names:
            n_in = meta.get("n_inputs", 1)
            names = [f"x{i}" for i in range(n_in)]
        self._input_names = list(names)
        self._inputs = {n: PredictorHandle(n) for n in self._input_names}
        self._outputs = []

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        """Run; `inputs` may be a list of numpy arrays (positional) for the
        one-shot convenience form, else use the copy_from_cpu handles."""
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        args = [self._inputs[n]._value for n in self._input_names]
        if any(a is None for a in args):
            missing = [n for n in self._input_names
                       if self._inputs[n]._value is None]
            raise ValueError(f"inputs not set: {missing}")
        out = self._layer(*args)
        outs = out if isinstance(out, (tuple, list)) else [out]
        self._outputs = []
        for i, o in enumerate(outs):
            h = PredictorHandle(f"out{i}")
            h._value = np.asarray(o.numpy() if hasattr(o, "numpy") else o)
            self._outputs.append(h)
        return [h._value for h in self._outputs]

    def get_output_names(self):
        return [h.name for h in self._outputs] or ["out0"]

    def get_output_handle(self, name):
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(name)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
