"""Native (C++) host-side data-pipeline kernels.

The reference implements its DataLoader hot path in C++
(paddle/fluid/framework/data_feed.cc; multiprocess workers feed batches
through shared memory). On TPU the device side is XLA's, but batch
collation and image normalization still run on the host per step — this
module compiles `batcher.cc` once (g++ -O3, cached .so beside the source)
and exposes it through ctypes. Everything degrades gracefully to numpy
when no toolchain is available, so the package never hard-depends on a
compiler at runtime.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["available", "collate", "normalize_images", "load_library"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "batcher.cc")
_SO = os.path.join(_HERE, "_batcher.so")
_lock = threading.Lock()
_lib = [None, False]   # (handle, attempted)


def _build():
    # build to a unique temp path, then atomic-rename: forked DataLoader
    # workers may race here, and another process must never dlopen a
    # partially written ELF
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", tmp]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, _SO)


def load_library():
    """Compile (if stale) and load the native library; None on failure."""
    with _lock:
        if _lib[1]:
            return _lib[0]
        _lib[1] = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.pt_collate.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32]
            lib.pt_normalize_batch.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_float, ctypes.c_int32]
            _lib[0] = lib
        except Exception:
            _lib[0] = None
        return _lib[0]


def available() -> bool:
    return load_library() is not None


_COLLATE_MIN_BYTES = 1 << 16   # small batches: numpy stack is fine


def collate(samples, n_threads: int = 4):
    """Stack a list of equal-shape/dtype numpy arrays into one batch array
    using the native multithreaded memcpy; falls back to np.stack."""
    lib = load_library()
    n = len(samples)
    if n == 0:
        raise ValueError("empty batch")
    first = samples[0]
    sample_bytes = first.nbytes
    if (lib is None or n * sample_bytes < _COLLATE_MIN_BYTES
            or first.dtype.hasobject   # PyObject* must NOT be raw-memcpy'd
            or any(s.shape != first.shape or s.dtype != first.dtype
                   or not s.flags.c_contiguous for s in samples)):
        return np.stack(samples)
    out = np.empty((n,) + first.shape, first.dtype)
    ptrs = (ctypes.c_void_p * n)(
        *[s.ctypes.data_as(ctypes.c_void_p).value for s in samples])
    lib.pt_collate(ptrs, n, sample_bytes,
                   out.ctypes.data_as(ctypes.c_void_p), n_threads)
    return out


def normalize_images(images, mean, std, scale: float = 1.0 / 255.0,
                     n_threads: int = 4):
    """uint8 HWC images (list or [N,H,W,C] array) -> normalized f32 NCHW.
    The fused ToTensor+Normalize host kernel; numpy fallback otherwise."""
    if isinstance(images, np.ndarray) and images.ndim == 4:
        images = list(images)
    n = len(images)
    h, w, c = images[0].shape
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    lib = load_library()
    ok = (lib is not None
          and mean.size == c and std.size == c   # OOB read guard in C++
          and all(im.dtype == np.uint8 and im.shape == (h, w, c)
                  and im.flags.c_contiguous for im in images))
    if not ok:
        arr = np.stack(images).astype(np.float32) * scale
        arr = (arr - mean.reshape(1, 1, 1, c)) / std.reshape(1, 1, 1, c)
        return np.ascontiguousarray(arr.transpose(0, 3, 1, 2))
    out = np.empty((n, c, h, w), np.float32)
    ptrs = (ctypes.c_void_p * n)(
        *[im.ctypes.data_as(ctypes.c_void_p).value for im in images])
    lib.pt_normalize_batch(
        ptrs, out.ctypes.data_as(ctypes.c_void_p), n, h, w, c,
        mean.ctypes.data_as(ctypes.c_void_p),
        std.ctypes.data_as(ctypes.c_void_p), scale, n_threads)
    return out
