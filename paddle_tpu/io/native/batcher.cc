// Native host-side data-pipeline kernels (the C++ analog of the reference's
// paddle/fluid/framework/data_feed.cc batch assembly: the DataLoader's hot
// host path — batch collation and image normalization — runs in compiled
// code instead of the Python interpreter).
//
// Built by paddle_tpu/io/native/__init__.py with `g++ -O3 -shared -fPIC`
// and loaded via ctypes (no pybind dependency; plain C ABI).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Stack n equally-sized samples into one contiguous batch buffer.
// samples: array of n pointers, each to sample_bytes of data.
// Multithreaded memcpy for large batches (HBM-feed staging is
// memory-bandwidth-bound; threads saturate it).
void pt_collate(const void** samples, int64_t n, int64_t sample_bytes,
                void* out, int32_t n_threads) {
  char* dst = static_cast<char*>(out);
  if (n_threads <= 1 || n < 4) {
    for (int64_t i = 0; i < n; ++i) {
      std::memcpy(dst + i * sample_bytes, samples[i], sample_bytes);
    }
    return;
  }
  if (n_threads > n) n_threads = static_cast<int32_t>(n);
  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  int64_t per = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * per;
    int64_t hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(dst + i * sample_bytes, samples[i], sample_bytes);
      }
    });
  }
  for (auto& th : threads) th.join();
}

// uint8 HWC image -> normalized float32 CHW (the torchvision/paddle
// ToTensor+Normalize fusion, the per-image hot loop of vision input
// pipelines). mean/std are per-channel, scale applied first (1/255).
void pt_normalize_hwc_to_chw(const uint8_t* in, float* out, int64_t h,
                             int64_t w, int64_t c, const float* mean,
                             const float* stddev, float scale) {
  std::vector<float> inv(c);
  for (int64_t ch = 0; ch < c; ++ch) inv[ch] = 1.0f / stddev[ch];
  const int64_t hw = h * w;
  for (int64_t y = 0; y < h; ++y) {
    for (int64_t x = 0; x < w; ++x) {
      const uint8_t* px = in + (y * w + x) * c;
      for (int64_t ch = 0; ch < c; ++ch) {
        out[ch * hw + y * w + x] =
            (static_cast<float>(px[ch]) * scale - mean[ch]) * inv[ch];
      }
    }
  }
}

// Batched variant: n images in one call (one thread per slice of images).
void pt_normalize_batch(const uint8_t** imgs, float* out, int64_t n,
                        int64_t h, int64_t w, int64_t c, const float* mean,
                        const float* stddev, float scale, int32_t n_threads) {
  const int64_t img_elems = c * h * w;
  if (n_threads <= 1 || n < 2) {
    for (int64_t i = 0; i < n; ++i) {
      pt_normalize_hwc_to_chw(imgs[i], out + i * img_elems, h, w, c, mean,
                              stddev, scale);
    }
    return;
  }
  if (n_threads > n) n_threads = static_cast<int32_t>(n);
  std::vector<std::thread> threads;
  int64_t per = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * per;
    int64_t hi = lo + per < n ? lo + per : n;
    if (lo >= hi) break;
    threads.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i) {
        pt_normalize_hwc_to_chw(imgs[i], out + i * img_elems, h, w, c, mean,
                                stddev, scale);
      }
    });
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
