"""Data loading (reference: python/paddle/io/ — Dataset, DataLoader
reader.py:262 with multiprocess workers dataloader/dataloader_iter.py).

TPU-native notes: batches are collated in numpy on host (cheap) and converted
to device arrays on first use; per-host sharded iteration for data parallelism
is provided by DistributedBatchSampler, same as the reference.
"""
from __future__ import annotations

import bisect
import itertools
import math
import multiprocessing as mp
import queue as queue_mod
import threading
from typing import Iterable, List, Optional

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split",
           "DataLoader", "BatchSampler", "Sampler", "SequenceSampler",
           "RandomSampler", "WeightedRandomSampler", "DistributedBatchSampler",
           "default_collate_fn", "get_worker_info", "SubsetRandomSampler"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0)
        return self.datasets[ds_idx][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * f)) for f in lengths]
        lengths[-1] += n - sum(lengths)
    perm = np.random.permutation(sum(lengths))
    out = []
    off = 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray([w._value if isinstance(w, Tensor) else w
                                   for w in weights], dtype=np.float64).reshape(-1)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharded batches (reference io/dataloader/batch_sampler.py
    DistributedBatchSampler): pads/subsamples so every rank sees the same
    number of batches."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) * 1.0 / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class _WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    """Stack samples into batched Tensors (reference
    io/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        import jax.numpy as jnp
        return Tensor(jnp.stack([b._value for b in batch]))
    if isinstance(sample, np.ndarray):
        # native multithreaded collate for big uniform batches (the C++
        # data_feed.cc batch-assembly analog; numpy fallback inside)
        from . import native as _native
        return Tensor(_native.collate(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = zip(*batch)
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id, num_workers):
    global _worker_info
    _worker_info = _WorkerInfo(worker_id, num_workers, dataset)
    while True:
        item = index_queue.get()
        if item is None:
            break
        seq, indices = item
        try:
            samples = [dataset[i] for i in indices]
            batch = collate_fn(samples)
            # tensors are host numpy at this point for picklability
            batch = _to_numpy(batch)
            data_queue.put((seq, batch, None))
        except Exception as e:  # propagate worker errors
            data_queue.put((seq, None, e))


def _to_numpy(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy(v) for k, v in obj.items()}
    return obj


def _to_tensor(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_tensor(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_tensor(v) for k, v in obj.items()}
    return obj


class DataLoader:
    """reference io/reader.py:262 DataLoader: single or multi-process loading
    with prefetch."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
        elif self.num_workers == 0:
            for indices in self.batch_sampler:
                samples = [self.dataset[i] for i in indices]
                yield self.collate_fn(samples)
        else:
            yield from self._iter_multiprocess()

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_multiprocess(self):
        ctx = mp.get_context("fork")
        index_queues = []
        data_queue = ctx.Queue()
        workers = []
        for wid in range(self.num_workers):
            iq = ctx.Queue()
            w = ctx.Process(target=_worker_loop,
                            args=(self.dataset, iq, data_queue, self.collate_fn,
                                  wid, self.num_workers), daemon=True)
            w.start()
            workers.append(w)
            index_queues.append(iq)
        try:
            batches = list(self.batch_sampler)
            n = len(batches)
            inflight = 0
            next_submit = 0
            next_yield = 0
            buffered = {}
            max_inflight = self.num_workers * self.prefetch_factor
            while next_submit < n and inflight < max_inflight:
                index_queues[next_submit % self.num_workers].put(
                    (next_submit, batches[next_submit]))
                next_submit += 1
                inflight += 1
            while next_yield < n:
                while next_yield not in buffered:
                    seq, data, err = data_queue.get(
                        timeout=self.timeout if self.timeout else None)
                    if err is not None:
                        raise err
                    buffered[seq] = data
                    inflight -= 1
                    if next_submit < n:
                        index_queues[next_submit % self.num_workers].put(
                            (next_submit, batches[next_submit]))
                        next_submit += 1
                        inflight += 1
                yield _to_tensor(buffered.pop(next_yield))
                next_yield += 1
        finally:
            for iq in index_queues:
                iq.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
