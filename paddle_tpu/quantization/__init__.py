"""Quantization (reference: python/paddle/quantization/ — QAT/PTQ factories,
observers). Initial TPU surface: fake-quant ops (int8/fp8 simulated) +
QuantConfig/QAT wrappers; native fp8 matmul lands with the Pallas quant
kernels (pallas_guide 'Quantization Kernels' pattern).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import op_call
from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "quanter", "fake_quant_abs_max",
           "AbsMaxObserver", "EMAObserver", "quantize_weight",
           "dequantize_weight"]


def fake_quant_abs_max(x, bit_length=8):
    def impl(v):
        qmax = 2.0 ** (bit_length - 1) - 1
        scale = jnp.max(jnp.abs(v)) / qmax
        q = jnp.round(v / jnp.maximum(scale, 1e-8))
        q = jnp.clip(q, -qmax - 1, qmax)
        deq = q * scale
        # straight-through estimator
        return v + jax.lax.stop_gradient(deq - v)
    return op_call("fake_quant_abs_max", impl, x)


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        self._layer_configs[id(layer)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._layer_configs[layer_type] = (activation, weight)


def quanter(name=None, **kwargs):
    def deco(cls):
        return cls
    return deco


class _FakeQuantLinearHook:
    def __init__(self, bits=8):
        self.bits = bits

    def __call__(self, layer, inputs):
        return tuple(fake_quant_abs_max(i, self.bits) if isinstance(i, Tensor) else i
                     for i in inputs)


class QAT:
    """Quantization-aware training: wraps Linear/Conv with fake-quant hooks."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False):
        from ..nn import Linear, Conv2D
        for sub in model.sublayers(include_self=True):
            if isinstance(sub, (Linear, Conv2D)):
                sub.register_forward_pre_hook(_FakeQuantLinearHook())
        return model

    def convert(self, model: Layer, inplace=False):
        return model


class AbsMaxObserver:
    """Tracks the running abs-max of a tensor stream (reference
    quantization/observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        self.bits = quant_bits
        self.absmax = 0.0

    def update(self, value):
        import numpy as np
        v = value._value if isinstance(value, Tensor) else value
        self.absmax = max(self.absmax, float(jnp.max(jnp.abs(v))))

    def scale(self):
        qmax = 2.0 ** (self.bits - 1) - 1
        return max(self.absmax, 1e-8) / qmax


class EMAObserver(AbsMaxObserver):
    """Exponential-moving-average abs-max (reference mse/ema observers)."""

    def __init__(self, quant_bits=8, momentum=0.9):
        super().__init__(quant_bits)
        self.momentum = momentum
        self._seen = False

    def update(self, value):
        v = value._value if isinstance(value, Tensor) else value
        cur = float(jnp.max(jnp.abs(v)))
        if not self._seen:
            self.absmax = cur
            self._seen = True
        else:
            self.absmax = self.momentum * self.absmax + (1 - self.momentum) * cur


def quantize_weight(w, bits=8, axis=None):
    """-> (int values, scale): symmetric absmax quantization (int8 storage
    up to 8 bits, int32 above).

    ``axis=None`` keeps the historical PER-TENSOR behavior (scalar scale).
    ``axis`` (an int or tuple of ints) selects PER-CHANNEL quantization:
    the absmax reduces over exactly those axes and the returned scale
    keeps them as size-1 dims (``keepdims``), so ``q * scale`` broadcasts
    back without bookkeeping.  For a ``[in, out]`` matmul weight,
    ``axis=-2`` (reduce the contraction axis) gives one scale per output
    channel — the granularity attention projections need: with one
    per-tensor scale, a single hot channel flattens every other head's
    resolution to a handful of int8 codes."""
    qmax = 2.0 ** (bits - 1) - 1
    if axis is None:
        absmax = jnp.max(jnp.abs(w))
    else:
        absmax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / qmax
    idtype = jnp.int8 if bits <= 8 else jnp.int32
    q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax).astype(idtype)
    return q, scale


def dequantize_weight(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_weight`: ``scale`` is the scalar (per-
    tensor) or keepdims array (per-channel) that function returned —
    either broadcasts straight through the multiply."""
    return q.astype(dtype) * jnp.asarray(scale, dtype)


class PTQ(QAT):
    """Post-training quantization: calibrate activation observers over
    sample data, then convert — weights snap to the int8 grid and each
    quantized layer records its activation/weight scales (reference
    quantization/ptq.py flow)."""

    def __init__(self, config: QuantConfig = None):
        super().__init__(config or QuantConfig())
        self._observers = {}

    def _make_activation_observer(self):
        """Honor QuantConfig.activation: an observer instance (used as a
        template) or class; default AbsMaxObserver(8)."""
        tmpl = self.config.activation
        if tmpl is None:
            return AbsMaxObserver()
        if isinstance(tmpl, type):
            return tmpl()
        obs = type(tmpl)(quant_bits=tmpl.bits)
        if isinstance(tmpl, EMAObserver):
            obs.momentum = tmpl.momentum
        return obs

    def _weight_bits(self):
        w = self.config.weight
        if w is None:
            return 8
        return getattr(w, "bits", w if isinstance(w, int) else 8)

    def quantize(self, model: Layer, inplace=False):
        """Install calibration observers (run sample batches afterwards)."""
        from ..nn import Linear, Conv2D
        for name, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, (Linear, Conv2D)):
                obs = self._make_activation_observer()

                def hook(layer, inputs, _obs=obs):
                    for i in inputs:
                        if isinstance(i, Tensor):
                            _obs.update(i)
                    return inputs
                handle = sub.register_forward_pre_hook(hook)
                self._observers[name] = (sub, obs, handle)
        return model

    def convert(self, model: Layer, inplace=False):
        """Bake scales: weights move onto the int8 grid (stored dequantized
        for TPU matmul; int values + scales attached for serialization).
        Calibration hooks are removed — converted models jit cleanly."""
        bits = self._weight_bits()
        for name, (sub, obs, handle) in self._observers.items():
            try:
                handle.remove()
            except Exception:
                pass
            q, w_scale = quantize_weight(sub.weight._value, bits=bits)
            sub.weight._set_value(dequantize_weight(q, w_scale,
                                                    sub.weight._value.dtype))
            sub.weight_quant = {"int_values": q, "scale": float(w_scale)}
            sub.activation_scale = obs.scale()
        self._observers = {}
        return model
