"""Quantization (reference: python/paddle/quantization/ — QAT/PTQ factories,
observers). Initial TPU surface: fake-quant ops (int8/fp8 simulated) +
QuantConfig/QAT wrappers; native fp8 matmul lands with the Pallas quant
kernels (pallas_guide 'Quantization Kernels' pattern).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import op_call
from ..core.tensor import Tensor
from ..nn.layer import Layer

__all__ = ["QuantConfig", "QAT", "PTQ", "quanter", "fake_quant_abs_max"]


def fake_quant_abs_max(x, bit_length=8):
    def impl(v):
        qmax = 2.0 ** (bit_length - 1) - 1
        scale = jnp.max(jnp.abs(v)) / qmax
        q = jnp.round(v / jnp.maximum(scale, 1e-8))
        q = jnp.clip(q, -qmax - 1, qmax)
        deq = q * scale
        # straight-through estimator
        return v + jax.lax.stop_gradient(deq - v)
    return op_call("fake_quant_abs_max", impl, x)


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        self._layer_configs[id(layer)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._layer_configs[layer_type] = (activation, weight)


def quanter(name=None, **kwargs):
    def deco(cls):
        return cls
    return deco


class _FakeQuantLinearHook:
    def __init__(self, bits=8):
        self.bits = bits

    def __call__(self, layer, inputs):
        return tuple(fake_quant_abs_max(i, self.bits) if isinstance(i, Tensor) else i
                     for i in inputs)


class QAT:
    """Quantization-aware training: wraps Linear/Conv with fake-quant hooks."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace=False):
        from ..nn import Linear, Conv2D
        for sub in model.sublayers(include_self=True):
            if isinstance(sub, (Linear, Conv2D)):
                sub.register_forward_pre_hook(_FakeQuantLinearHook())
        return model

    def convert(self, model: Layer, inplace=False):
        return model


class PTQ(QAT):
    pass
